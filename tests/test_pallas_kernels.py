"""Pallas kernel differential tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops.pallas_kernels import lrn_pallas, pallas_matmul


def lrn_ref(x, nsize, alpha, beta, knorm):
    """Pure-jnp LRN (the XLA path in layers/norm.py)."""
    c = x.shape[-1]
    half_lo = (nsize - 1) // 2
    sq = x * x
    out = np.zeros_like(x)
    for ch in range(c):
        lo = max(0, ch - half_lo)
        hi = min(c, ch + (nsize - 1 - half_lo) + 1)
        norm = knorm + alpha / nsize * np.sum(sq[..., lo:hi], axis=-1)
        out[..., ch] = x[..., ch] * norm ** -beta
    return out


@pytest.mark.parametrize('nsize', [3, 5, 4])
def test_lrn_pallas_forward(nsize):
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 5, 96).astype(np.float32)
    out = np.asarray(lrn_pallas(jnp.asarray(x), nsize, 0.001, 0.75, 1.0))
    ref = lrn_ref(x, nsize, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('nsize', [5, 4])
def test_lrn_pallas_grad_matches_autodiff(nsize):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(2, 2, 3, 32).astype(np.float32) + 0.1)

    def jnp_lrn(x):
        c = x.shape[-1]
        half_lo = (nsize - 1) // 2
        half_hi = nsize - 1 - half_lo
        sq = x * x
        pad = jnp.pad(sq, [(0, 0)] * 3 + [(half_lo + 1, half_hi)])
        cums = jnp.cumsum(pad, axis=-1)
        win = cums[..., nsize:nsize + c] - cums[..., 0:c]
        norm = win * (0.001 / nsize) + 1.0
        return x * jnp.power(norm, -0.75)

    g_ref = jax.grad(lambda x: jnp.sum(jnp_lrn(x) ** 2))(x)
    g_pl = jax.grad(lambda x: jnp.sum(
        lrn_pallas(x, nsize, 0.001, 0.75, 1.0) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_pl), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize('nsize', [5, 4])
def test_lrn_hybrid_matches_full_pallas(nsize):
    """lrn_hybrid (pallas fwd / XLA bwd, the default TPU path at
    MXU-aligned channel counts) must agree with lrn_pallas in both
    passes."""
    from cxxnet_tpu.ops.pallas_kernels import lrn_hybrid
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.rand(2, 2, 3, 32).astype(np.float32) + 0.1)
    out_h = lrn_hybrid(x, nsize, 0.001, 0.75, 1.0)
    out_p = lrn_pallas(x, nsize, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(out_h), np.asarray(out_p),
                               rtol=1e-5, atol=1e-6)
    g_h = jax.grad(lambda x: jnp.sum(
        lrn_hybrid(x, nsize, 0.001, 0.75, 1.0) ** 2))(x)
    g_p = jax.grad(lambda x: jnp.sum(
        lrn_pallas(x, nsize, 0.001, 0.75, 1.0) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_h), np.asarray(g_p),
                               rtol=1e-4, atol=1e-5)


def test_lrn_auto_mode_gate(monkeypatch):
    """'auto' picks full Pallas at 128-lane-aligned channels, the
    fwd-only hybrid at other sublane-aligned counts, XLA for ragged
    channels or off-TPU; explicit on/off override both ways
    (receipts/micro_lrn.json)."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    monkeypatch.delenv('CXXNET_PALLAS', raising=False)
    assert pk.pallas_mode() == 'auto'
    # off a real TPU (interpret mode) auto never turns pallas on
    monkeypatch.setattr(pk, '_interpret', lambda: True)
    assert pk.lrn_auto_mode(256) == 'xla'
    monkeypatch.setattr(pk, '_interpret', lambda: False)
    assert pk.lrn_auto_mode(256) == 'full'     # norm2: fwd+bwd 2.16x
    assert pk.lrn_auto_mode(96) == 'hybrid'    # norm1: fwd 1.90x, bwd loses
    assert pk.lrn_auto_mode(50) == 'xla'       # ragged channel count
    assert pk.lrn_auto_mode(24) == 'xla'       # below the measured floor
    monkeypatch.setenv('CXXNET_PALLAS', '0')
    assert pk.lrn_auto_mode(256) == 'xla'
    monkeypatch.setenv('CXXNET_PALLAS', '1')
    assert pk.lrn_auto_mode(96) == 'full'


def test_lrn_pallas_under_jit():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.rand(4, 2, 2, 16).astype(np.float32))
    f = jax.jit(lambda x: lrn_pallas(x, 5, 0.001, 0.75, 1.0))
    np.testing.assert_allclose(np.asarray(f(x)),
                               lrn_ref(np.asarray(x), 5, 0.001, 0.75, 1.0),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('m,k,n', [(100, 64, 70), (256, 512, 256)])
def test_pallas_matmul(m, k, n):
    rng = np.random.RandomState(3)
    a = rng.randn(m, k).astype(np.float32)
    b = rng.randn(k, n).astype(np.float32)
    out = np.asarray(pallas_matmul(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-4)


def test_lrn_layer_uses_pallas_when_enabled(monkeypatch):
    monkeypatch.setenv('CXXNET_PALLAS', '1')
    from cxxnet_tpu.layers import ForwardContext, NodeSpec, create_layer
    from cxxnet_tpu.layers.base import get_layer_type
    rng = np.random.RandomState(4)
    x = rng.rand(2, 3, 3, 8).astype(np.float32)
    layer = create_layer(get_layer_type('lrn'))
    layer.set_param('local_size', '5')
    layer.infer_shapes([NodeSpec(8, 3, 3)])
    ctx = ForwardContext(is_train=False)
    out = layer.forward({}, [jnp.asarray(x)], ctx)[0]
    np.testing.assert_allclose(np.asarray(out),
                               lrn_ref(x, 5, 0.001, 0.75, 1.0),
                               rtol=1e-5, atol=1e-6)


def test_clamp_tile():
    """Default tiles shrink to the covered dim (lane-aligned): fullc's
    production m=256 must not be padded to the TN kernel's old fixed
    tile_m=512 (that halved its throughput, receipts/micro_matmul_bwd)."""
    from cxxnet_tpu.ops.pallas_kernels import _clamp_tile
    assert _clamp_tile(512, 256) == 256
    assert _clamp_tile(512, 1000) == 512
    assert _clamp_tile(256, 100) == 128
    assert _clamp_tile(128, 8) == 128


def test_pallas_matmul_grad():
    rng = np.random.RandomState(5)
    a = jnp.asarray(rng.randn(64, 48).astype(np.float32))
    b = jnp.asarray(rng.randn(48, 32).astype(np.float32))
    g = jnp.asarray(rng.randn(64, 32).astype(np.float32))
    da, db = jax.vjp(pallas_matmul, a, b)[1](g)
    np.testing.assert_allclose(np.asarray(da), np.asarray(g @ b.T),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(db), np.asarray(a.T @ g),
                               rtol=1e-4, atol=1e-4)


def test_lrn_pallas_rows_equal_channels():
    # regression: padded row count == channel count must not misroute the
    # band matrix (positional BlockSpec dispatch in _lrn_call)
    from cxxnet_tpu.ops import pallas_kernels as pk
    rng = np.random.RandomState(6)
    c = pk._ROW_TILE
    x = jnp.asarray(rng.rand(pk._ROW_TILE // 4, 2, 2, c).astype(np.float32))
    out = pk.lrn_pallas(x, 5, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(out),
                               lrn_ref(np.asarray(x), 5, 0.001, 0.75, 1.0),
                               rtol=1e-4, atol=1e-5)


class TestFlashAttention:
    def _rand(self, b, s, h, d, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        return mk(), mk(), mk()

    @pytest.mark.parametrize('causal', [False, True])
    def test_matches_reference(self, causal):
        from cxxnet_tpu.ops.pallas_kernels import flash_attention
        from cxxnet_tpu.parallel.sequence import attention_reference
        q, k, v = self._rand(2, 32, 2, 16)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    @pytest.mark.parametrize('causal', [False, True])
    def test_gradients_match(self, causal):
        from cxxnet_tpu.ops.pallas_kernels import flash_attention
        from cxxnet_tpu.parallel.sequence import attention_reference
        q, k, v = self._rand(1, 24, 2, 8, seed=1)

        def loss_f(f):
            return lambda q, k, v: jnp.sum(
                f(q, k, v) * jnp.cos(jnp.arange(q.size).reshape(q.shape)))

        f = lambda q, k, v: flash_attention(q, k, v, causal=causal,
                                            block_q=8, block_k=8)
        r = lambda q, k, v: attention_reference(q, k, v, causal=causal)
        g = jax.grad(loss_f(f), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_f(r), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(a, b, atol=2e-5)

    def test_ragged_seq_padding(self):
        # seq not a multiple of the block: padded keys must not leak
        from cxxnet_tpu.ops.pallas_kernels import flash_attention
        from cxxnet_tpu.parallel.sequence import attention_reference
        q, k, v = self._rand(1, 21, 2, 8, seed=2)
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_cross_attention_shapes(self):
        from cxxnet_tpu.ops.pallas_kernels import flash_attention
        from cxxnet_tpu.parallel.sequence import attention_reference
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(2, 12, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 40, 2, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 40, 2, 8), jnp.float32)
        out = flash_attention(q, k, v, block_q=8, block_k=8)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_ulysses_flash_path(self, monkeypatch):
        from cxxnet_tpu.parallel.sequence import (attention_reference,
                                                  ulysses_attention)
        from jax.sharding import Mesh
        monkeypatch.setenv('CXXNET_PALLAS', '1')
        devs = np.array(jax.devices()[:4])
        mesh = Mesh(devs, ('data',))
        q, k, v = self._rand(2, 32, 4, 8, seed=4)
        out = ulysses_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)


def test_attn_use_flash_gate(monkeypatch):
    """'auto' engages flash only on real TPU where the dense score
    matrix (batch*heads*seq^2 f32) blows the HBM budget; explicit on/off
    force both ways."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    monkeypatch.delenv('CXXNET_PALLAS', raising=False)
    monkeypatch.setattr(pk, '_interpret', lambda: True)
    assert not pk.attn_use_flash(16384, batch=2, heads=8)
    monkeypatch.setattr(pk, '_interpret', lambda: False)
    if pk.pltpu is not None:
        assert pk.attn_use_flash(16384, batch=2, heads=8)    # ~17 GB
        assert pk.attn_use_flash(4096, batch=64, heads=16)   # big b*h
    assert not pk.attn_use_flash(4096, batch=2, heads=8)     # ~1 GB
    assert not pk.attn_use_flash(16384)                      # b1 h1: fits
    monkeypatch.setenv('CXXNET_PALLAS', '1')
    assert pk.attn_use_flash(64)
    monkeypatch.setenv('CXXNET_PALLAS', '0')
    assert not pk.attn_use_flash(16384, batch=2, heads=8)


def test_lrn_auto_gate_scoped_to_single_device(monkeypatch):
    """The auto LRN hybrid must stand down inside multi-device GSPMD
    programs (no sharding rule for the opaque pallas_call); explicit
    use_pallas=1 still forces it.  The mesh size is threaded per-program
    through ForwardContext, not a process global."""
    from cxxnet_tpu.layers import ForwardContext
    from cxxnet_tpu.ops import pallas_kernels as pk
    monkeypatch.delenv('CXXNET_PALLAS', raising=False)
    monkeypatch.setattr(pk, '_interpret', lambda: False)
    assert pk.lrn_auto_mode(256, spmd_devices=1) == 'full'
    assert pk.lrn_auto_mode(256, spmd_devices=8) == 'xla'
    monkeypatch.setenv('CXXNET_PALLAS', '1')
    assert pk.lrn_auto_mode(256, spmd_devices=8) == 'full'
    assert ForwardContext(is_train=False).spmd_devices == 1


def test_matmul_wide_n_preset_numerics():
    """The measured-winning fc6 tile preset (MATMUL_TILES_WIDE_N,
    receipts/micro_matmul_tiles.log) must be numerically identical to the
    default tiling — it is a pure schedule change."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    rng = np.random.RandomState(6)
    a = jnp.asarray(rng.randn(64, 192).astype(np.float32))
    b = jnp.asarray(rng.randn(192, 96).astype(np.float32))
    out = pk._matmul_impl(a, b, *pk.MATMUL_TILES_WIDE_N)
    np.testing.assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b),
                               rtol=1e-4, atol=1e-4)
