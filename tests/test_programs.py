"""graftprof — compiler-truth observability suite (``-m obs``,
doc/observability.md "Programs, memory, and MFU").

The load-bearing claims:

* every ledger-routed program registers ONE entry per distinct
  signature with nonzero flops and memory_analysis fields (on CPU —
  the acceptance platform), and re-dispatch never recompiles,
* the recompile sentinel: a program past its declared bound bumps
  ``recompiles_total`` and records the typed ``RecompileStormError``
  kind under ``warn``, raises it under ``raise`` — including the
  PredictEngine bucket-mismatch drill (a caller bypassing the pad
  path),
* ``hbm.*`` gauges degrade to the live-array fallback on CPU
  (``supported=0``) instead of vanishing,
* ``budget_drift`` cross-checks the closed-form ``resident_bytes``
  ledgers against ``memory_analysis`` truth within a few percent,
* ``train_step_flops`` reads the live ledger (no throwaway compile),
* ``/programs`` serves the ledger live mid-run from the CLI, with the
  MFU gauge riding the eval line,
* the ``/profile`` session is single-flight and mutually exclusive
  with a config-driven TraceWindow,
* ``tools/bench_guard.py`` holds the receipt-ledger line (strict
  JSON, platform stamps, regression flags) over the committed repo.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from cxxnet_tpu.obs import TelemetryHub, install_hub
from cxxnet_tpu.obs.programs import (DeviceMemory, ProgramLedger,
                                     install_ledger, mfu, peak_flops,
                                     register_hbm)
from cxxnet_tpu.runtime import faults

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GUARD = os.path.join(REPO, 'tools', 'bench_guard.py')


@pytest.fixture
def ledger():
    led = ProgramLedger()
    prev = install_ledger(led)
    yield led
    install_ledger(prev)


@pytest.fixture
def hub():
    h = TelemetryHub(ring_events=256)
    prev = install_hub(h)
    yield h
    h.disarm()
    install_hub(prev)


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read()


# --- ledger units -----------------------------------------------------------

def test_entry_has_cost_and_memory_truth(ledger):
    import jax.numpy as jnp
    prog = ledger.program('t.mm')
    fn = prog.jit(lambda a, b: a @ b,
                  key_fn=lambda a, _k: f'n{a[0].shape[0]}')
    x = jnp.ones((64, 32))
    y = jnp.ones((32, 16))
    out = fn(x, y)
    assert out.shape == (64, 16)
    fn(x, y)                             # cached: no recompile
    e = prog.newest_entry()
    assert e.name == 't.mm' and e.shape_key == 'n64'
    assert e.compiles == 1 and prog.compiles == 1
    assert e.compile_ms > 0
    assert e.flops > 0                   # cost_analysis truth (CPU too)
    assert e.argument_bytes == (64 * 32 + 32 * 16) * 4
    assert e.output_bytes == 64 * 16 * 4
    assert e.peak_bytes >= e.argument_bytes + e.output_bytes
    assert 'float32[64,32]' in e.signature
    assert prog.flops_per_step() == e.flops


def test_distinct_signatures_row_separately(ledger):
    import jax.numpy as jnp
    prog = ledger.program('t.sq')
    fn = prog.jit(lambda a: a * a)       # auto shape keys
    fn(jnp.ones((8,)))
    fn(jnp.ones((16,)))
    fn(jnp.ones((8,)))                   # cached
    assert prog.compiles == 2
    assert [e.shape_key for e in prog.entries()] == ['v0', 'v1']
    assert ledger.summary()['compiles_total'] == 2


def test_retired_program_skips_lazy_aot_probe(ledger):
    """An engine retiring its programs on close must stop the lazy AOT
    sweep from re-lowering its (possibly SPMD) skeletons: retired
    entries keep their rows but read zero compiler truth, and a full
    ``entries()`` sweep afterwards adds no compile cost."""
    import jax.numpy as jnp
    prog = ledger.program('t.retired')
    fn = prog.jit(lambda a: a + 1)
    fn(jnp.ones((8,)))                   # records, analysis still lazy
    prog.retire()
    rows = ledger.entries()              # sweep: must NOT probe t.retired
    (e,) = [r for r in rows if r.name == 't.retired']
    assert e.compiles == 1               # the row survives retirement
    assert e.flops == 0 and e.compile_ms == 0
    assert ledger.summary()['compile_ms_total'] == 0
    prog.retire()                        # idempotent


def test_reclaimed_name_gets_suffix(ledger):
    a = ledger.program('serve.predict')
    b = ledger.program('serve.predict')
    assert a.name == 'serve.predict'
    assert b.name == 'serve.predict#2'


def test_sentinel_warn_records_typed_kind(ledger, capsys):
    import jax.numpy as jnp
    log = faults.global_failure_log()
    before = sum(1 for r in log.records()
                 if r.kind == 'RecompileStormError')
    prog = ledger.program('t.bounded', bound=1)
    fn = prog.jit(lambda a: a + 1)
    fn(jnp.ones((4,)))
    fn(jnp.ones((5,)))                   # second compile: past the bound
    assert ledger.recompiles_total == 1
    after = sum(1 for r in log.records()
                if r.kind == 'RecompileStormError')
    assert after == before + 1
    assert 'recompile storm' in capsys.readouterr().err


def test_sentinel_raise_leg(ledger):
    import jax.numpy as jnp
    ledger.set_recompile('raise')
    prog = ledger.program('t.bounded', bound=1)
    fn = prog.jit(lambda a: a + 1)
    fn(jnp.ones((4,)))
    with pytest.raises(faults.RecompileStormError) as ei:
        fn(jnp.ones((5,)))
    assert ei.value.bound == 1 and ei.value.compiles == 2
    ledger.set_recompile('off')
    fn(jnp.ones((6,)))                   # off: counted nowhere, no raise
    assert ledger.recompiles_total == 1


def test_bad_recompile_mode_rejected(ledger):
    with pytest.raises(ValueError, match='warn|raise|off'):
        ledger.set_recompile('maybe')


# --- PredictEngine: the bucket-mismatch recompile-storm drill ---------------

def _mlp_engine(ledger, buckets=(4, 8)):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.serve.engine import PredictEngine
    from cxxnet_tpu.utils.config import parse_config_string
    from tests.test_net_mnist import MLP_CONF
    tr = NetTrainer(parse_config_string(
        MLP_CONF + 'inference_only = 1\n'))
    tr.init_model()
    return PredictEngine(tr, buckets)


def test_predict_engine_rebased_compile_count_and_drill(ledger):
    eng = _mlp_engine(ledger)
    assert eng.compile_count == 0
    assert eng.warm() == 2               # one program per bucket
    ks = sorted(e.shape_key for e in eng._program.entries())
    assert ks == ['b4', 'b8']
    rng = np.random.RandomState(0)
    out = eng.predict_scores(rng.randn(5, 1, 1, 16).astype(np.float32))
    assert out.shape[0] == 5
    assert eng.compile_count == 2        # padded onto the ladder: no growth
    # the drill: a buggy caller bypasses the pad path with a novel
    # shape — the sentinel sees the third compile against bound=2
    bad = eng._put(rng.randn(3, 1, 1, 16).astype(np.float32))
    eng._fwd(eng.params, bad)
    assert ledger.recompiles_total == 1
    ledger.set_recompile('raise')
    with pytest.raises(faults.RecompileStormError, match='serve.predict'):
        eng._fwd(eng.params, eng._put(
            rng.randn(7, 1, 1, 16).astype(np.float32)))


def test_predict_engine_ledger_bytes_close_to_resident(ledger):
    eng = _mlp_engine(ledger)
    eng.warm()
    truth = eng.ledger_bytes()
    assert truth is not None and truth > 0
    closed = eng.resident_bytes()
    assert abs(closed / truth - 1.0) < 0.05, (closed, truth)


# --- decode engine: /programs rows + budget_drift ---------------------------

def test_decode_programs_and_budget_drift(ledger):
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.serve.decode import DecodeService
    cfg = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                              d_ff=64, num_stages=2, seq_len=32,
                              attn='local')
    params = T.init_params(np.random.RandomState(0), cfg)
    svc = DecodeService(params, cfg, slots=2, pages=24, page_size=4,
                        max_prompt=8, max_new_bound=6, deadline=60.0)
    try:
        prompt = np.arange(5, dtype=np.int32).reshape(1, 5)
        toks = svc.generate(prompt, 6)
        assert len(toks) == 6
        names = {e.name: e for e in ledger.entries()}
        assert 'decode.step' in names and names['decode.step'].flops > 0
        assert any(n.startswith('decode.prefill') for n in names)
        assert names['decode.step'].argument_bytes > 0
        drift = svc.engine.budget_drift()
        assert drift is not None
        # closed-form resident vs memory_analysis argument bytes: the
        # step's non-pool operands are O(slots) scalars, so the two
        # ledgers must agree within a few percent
        assert abs(drift) < 0.05, drift
        assert 'budget_drift' in svc.report('decode')
    finally:
        svc.close(30.0)


# --- hbm gauges -------------------------------------------------------------

def test_hbm_cpu_fallback_reports_live_bytes(hub):
    import jax
    import jax.numpy as jnp
    keep = jnp.ones((256, 256))          # something live to account
    register_hbm(hub)
    snap = hub.gauge_snapshot()
    assert snap.get('hbm.supported') == 0.0   # cpu: no memory_stats()
    in_use = snap.get('hbm.bytes_in_use[d0]')
    assert in_use is not None and in_use >= keep.nbytes
    assert snap.get('hbm.peak_bytes[d0]') >= in_use
    # the fallback's peak is an in-process monotone max
    dm = DeviceMemory()
    from cxxnet_tpu.utils.metric import StatSet
    st = StatSet()
    dm.fill(st)
    first = st.get('peak_bytes[d0]')
    del keep
    dm.fill(st)
    assert st.get('peak_bytes[d0]') >= 0
    assert max(dm._peak_seen.values()) >= first
    assert jax is not None


# --- MFU table --------------------------------------------------------------

def test_peak_flops_env_override_and_mfu(monkeypatch):
    monkeypatch.delenv('CXXNET_PEAK_TFLOPS', raising=False)
    assert peak_flops() == 0.0           # cpu: unknown denominator
    assert mfu(1e9, 10.0) is None        # ...so MFU is unreported
    monkeypatch.setenv('CXXNET_PEAK_TFLOPS', '0.5')
    assert peak_flops() == 0.5e12
    assert mfu(1e9, 10.0) == pytest.approx(1e10 / 0.5e12)
    assert mfu(0.0, 10.0) is None        # no flops -> no claim


def test_train_step_flops_reads_live_ledger(ledger):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    from tests.test_net_mnist import MLP_CONF, synth_batches
    tr = NetTrainer(parse_config_string(MLP_CONF))
    tr.init_model()
    assert tr.train_step_flops() == 0.0  # nothing compiled yet
    for batch in synth_batches(1, 16, seed=0):
        tr.update(batch)
    flops = tr.train_step_flops()        # no args: ledger-only read
    assert flops > 0
    e = tr._prog_step.newest_entry()
    assert flops == e.flops
    compiles = ledger.summary()['compiles_total']
    assert tr.train_step_flops() == flops
    # the read is free: no probe program was compiled for it
    assert ledger.summary()['compiles_total'] == compiles


# --- endpoints: /programs + /profile ----------------------------------------

def test_programs_endpoint_and_statusz(ledger, hub, tmp_path):
    import jax.numpy as jnp
    from cxxnet_tpu.obs.endpoints import ObsServer
    ledger.register_into(hub)
    prog = ledger.program('t.mm')
    prog.jit(lambda a: a @ a)(jnp.ones((16, 16)))
    srv = ObsServer(hub, port=0, profile_dir=str(tmp_path / 'prof'))
    try:
        body = json.loads(_get(f'{srv.url}/programs'))
        assert body['compiles_total'] == 1
        (entry,) = body['programs']
        assert entry['name'] == 't.mm' and entry['flops'] > 0
        assert entry['argument_bytes'] > 0
        status = json.loads(_get(f'{srv.url}/statusz'))
        assert status['status']['programs']['compiles_total'] == 1
        metrics = _get(f'{srv.url}/metrics').decode()
        assert 'cxxnet_programs_compiles_total 1' in metrics
        assert 'cxxnet_programs_flops{tag="t.mm"}' in metrics
    finally:
        srv.close(timeout=5.0)


def test_profile_endpoint_single_flight(ledger, hub, tmp_path):
    from cxxnet_tpu.obs.endpoints import ObsServer
    from cxxnet_tpu.obs.programs import ProfilerSession, profile_session
    import cxxnet_tpu.obs.programs as programs_mod
    prev = programs_mod._PROFILE
    programs_mod._PROFILE = ProfilerSession()  # fresh single-flight state
    srv = ObsServer(hub, port=0, profile_dir=str(tmp_path / 'prof'))
    try:
        # generous timeout: the FIRST start_trace initializes the
        # profiler backend, which can take seconds on a loaded host
        with urllib.request.urlopen(f'{srv.url}/profile?ms=200',
                                    timeout=60) as r:
            first = json.loads(r.read())
        assert first['started'] is True
        assert os.path.isdir(first['path'])
        second = json.loads(_get(f'{srv.url}/profile?ms=200'))
        assert second['started'] is False and 'busy' in second
        # stop_trace serializes metadata for EVERY compiled program in
        # the process — seconds when this runs late in a full suite
        # that compiled hundreds of executables, so wait generously
        deadline = time.monotonic() + 60
        while profile_session().status()['active'] \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        st = profile_session().status()
        assert st['active'] is None and st['sessions'] == 1
    finally:
        srv.close(timeout=5.0)
        deadline = time.monotonic() + 60
        while programs_mod._PROFILE.status()['active'] \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        programs_mod._PROFILE = prev


def test_profile_excluded_while_tracewindow_active(tmp_path):
    from cxxnet_tpu.obs.programs import ProfilerSession
    from cxxnet_tpu.utils import profiler as prof
    assert prof.acquire_trace('profile_dir')   # a TraceWindow is live
    try:
        res = ProfilerSession().start(str(tmp_path), ms=100)
        assert res['started'] is False
        assert res['busy'] == 'profile_dir'
    finally:
        prof.release_trace('profile_dir')
    assert prof.trace_owner() is None


# --- CLI e2e: /programs live mid-run, MFU on the eval line ------------------

def test_cli_train_programs_live_and_mfu_line(tmp_path):
    """task=train with obs.port=0: /programs answers mid-run with the
    trainer's compiled programs (nonzero flops + memory fields), and —
    with a declared peak — the MFU gauge rides the eval line."""
    from tests.test_io import write_mnist
    write_mnist(str(tmp_path), n=512, rows=8, cols=8, seed=4)
    conf = tmp_path / 'train.conf'
    conf.write_text(f"""
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 0
iter = end
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 3
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 16
dev = cpu
eta = 0.05
metric[label] = error
num_round = 6
obs.port = 0
""")
    env = dict(os.environ, JAX_PLATFORMS='cpu', CXXNET_PEAK_TFLOPS='0.001',
               PYTHONPATH=REPO + os.pathsep + os.environ.get('PYTHONPATH',
                                                             ''))
    out_path = tmp_path / 'stdout.txt'
    got = None
    with open(out_path, 'w') as out_f:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'cxxnet_tpu.main', str(conf)],
            cwd=str(tmp_path), env=env, stdout=out_f,
            stderr=subprocess.STDOUT, text=True)
        try:
            port = None
            deadline = time.monotonic() + 120
            while port is None and time.monotonic() < deadline:
                for line in out_path.read_text().splitlines():
                    if line.startswith('obs: telemetry on http://'):
                        assert '/programs' in line and '/profile' in line
                        port = int(line.split(':')[3].split('/')[0]
                                   .split()[0])
                        break
                if port is None:
                    assert proc.poll() is None, out_path.read_text()
                    time.sleep(0.05)
            assert port is not None, out_path.read_text()
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline and proc.poll() is None:
                try:
                    v = json.loads(_get(
                        f'http://127.0.0.1:{port}/programs'))
                except OSError:
                    time.sleep(0.05)
                    continue
                if v['programs']:
                    got = v              # LIVE mid-run snapshot
                    break
                time.sleep(0.05)
            proc.wait(timeout=180)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
    assert proc.returncode == 0, out_path.read_text()
    assert got is not None, out_path.read_text()
    by_name = {e['name']: e for e in got['programs']}
    step = by_name.get('train.step') or by_name.get('train.multi_step')
    assert step is not None, by_name
    assert step['flops'] > 0 and step['compile_ms'] > 0
    assert step['argument_bytes'] > 0 and step['peak_bytes'] > 0
    out = out_path.read_text()
    eval_lines = [ln for ln in out.splitlines() if 'train-mfu:' in ln]
    assert eval_lines, out
    assert 'train-flops_per_step:' in eval_lines[0]
    assert 'train-steps_per_sec:' in eval_lines[0]
    mfu_val = float(eval_lines[0].split('train-mfu:')[1].split('\t')[0])
    assert mfu_val > 0


def test_wrapper_and_capi_obs_programs_surface(ledger):
    """Embedders read /programs without a port: Net.obs_programs /
    net_obs_programs return the ledger view as JSON."""
    from cxxnet_tpu import capi, wrapper
    eng = _mlp_engine(ledger)
    eng.warm()
    net = wrapper.Net(dev='cpu')
    body = json.loads(net.obs_programs())
    assert body['compiles_total'] == 2
    assert {e['shape_key'] for e in body['programs']} == {'b4', 'b8'}
    assert capi.net_obs_programs(net) == net.obs_programs()


# --- bench_guard ------------------------------------------------------------

def _run_guard(*args):
    return subprocess.run([sys.executable, GUARD, *args],
                          capture_output=True, text=True)


def test_bench_guard_repo_ledger_clean():
    r = _run_guard()
    assert r.returncode == 0, r.stdout + r.stderr
    assert 'error(s)' in r.stdout


def test_bench_guard_rejects_nan_and_missing_platform(tmp_path):
    (tmp_path / 'BENCH_X_r01.json').write_text(
        '{"metric": "m", "value": NaN, "unit": "ms"}\n')
    r = _run_guard(str(tmp_path))
    assert r.returncode == 1
    assert 'null-not-NaN' in r.stdout
    (tmp_path / 'BENCH_X_r01.json').write_text(
        '{"metric": "m", "value": 1.0, "unit": "ms"}\n')
    r = _run_guard(str(tmp_path))
    assert r.returncode == 1
    assert 'platform' in r.stdout
    # a measured payload WITH a stamp (and an unmeasured one without)
    (tmp_path / 'BENCH_X_r01.json').write_text(json.dumps(
        {'metric': 'm', 'value': 1.0, 'unit': 'ms', 'platform': 'cpu'}))
    (tmp_path / 'BENCH_X_r02.json').write_text(json.dumps(
        {'metric': 'm', 'value': None, 'unit': None, 'error': 'down'}))
    r = _run_guard(str(tmp_path))
    assert r.returncode == 0, r.stdout


def test_bench_guard_flags_regressions_by_direction(tmp_path):
    mk = lambda **kw: json.dumps(dict(platform='cpu', **kw))  # noqa: E731
    (tmp_path / 'BENCH_S_r01.json').write_text(mk(
        metric='tok', value=1000.0, unit='tokens/sec'))
    (tmp_path / 'BENCH_S_r02.json').write_text(mk(
        metric='tok', value=500.0, unit='tokens/sec'))   # fell 50%
    (tmp_path / 'BENCH_L_r01.json').write_text(mk(
        metric='p99_ms', value=10.0, unit='ms'))
    (tmp_path / 'BENCH_L_r02.json').write_text(mk(
        metric='p99_ms', value=20.0, unit='ms'))          # rose 100%
    r = _run_guard(str(tmp_path))
    assert r.returncode == 0                 # flags warn by default
    assert 'BENCH_S: tok fell 50%' in r.stdout
    assert 'BENCH_L: p99_ms rose 100%' in r.stdout
    assert _run_guard(str(tmp_path), '--strict').returncode == 1
    # within tolerance: silent
    (tmp_path / 'BENCH_S_r02.json').write_text(mk(
        metric='tok', value=900.0, unit='tokens/sec'))
    (tmp_path / 'BENCH_L_r02.json').write_text(mk(
        metric='p99_ms', value=11.0, unit='ms'))
    r = _run_guard(str(tmp_path), '--strict')
    assert r.returncode == 0, r.stdout
