"""Quantized-inference tier suite (nnet/quantize.py, doc/serving.md
"Quantized inference").

The twin policy under test is two-sided:

* **exact twins** — a quantized model is a *different but deterministic*
  model, so its serving outputs have bitwise oracles: a quantized
  ``DecodeEngine``'s streams equal ``transformer.generate`` over the
  engine's own quantized tree + compute config; a quantized
  ``PredictEngine``'s scores equal an f32 engine fed the dequantized
  tree; the W8A8 ``qdot`` leg is bitwise-identical between the Pallas
  MXU kernel and the XLA ``dot_general`` fallback (exact int32
  accumulation).
* **pinned tolerance twins** — the accuracy delta vs f32 is policed by
  thresholds written HERE (top-1 agreement, logit error bounds):
  loosening one is a visible diff, never silent.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.models import transformer as T
from cxxnet_tpu.nnet import quantize as Q
from cxxnet_tpu.ops import pallas_kernels as PK
from cxxnet_tpu.serve import PredictEngine
from cxxnet_tpu.serve.decode import DecodeEngine

pytestmark = pytest.mark.quant

CFG = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                          d_ff=48, num_stages=2, seq_len=32, attn='local')
CFG_BF16 = dataclasses.replace(CFG, dtype=jnp.bfloat16)


def _params(seed=0):
    return T.init_params(np.random.RandomState(seed), CFG)


def _lm_int8(params):
    return Q.quantize_tree(params, 'int8', out_dtype=jnp.bfloat16,
                           quant_key=Q.lm_quant_key)


# --- QuantLeaf / quantize_tree mechanics ------------------------------------

def test_quantize_leaf_roundtrip_error_bound():
    """Symmetric per-channel int8: |x - q*scale| <= scale/2 everywhere
    (round-to-nearest), per channel."""
    rng = np.random.RandomState(0)
    x = (rng.randn(64, 48) * rng.uniform(0.1, 5.0, 48)).astype(np.float32)
    leaf = Q.quantize_leaf(x)
    assert leaf.q.dtype == np.int8 and leaf.scale.shape == (48,)
    deq = np.asarray(leaf.dequantize(np.float32))
    assert (np.abs(deq - x) <= leaf.scale[None, :] / 2 + 1e-7).all()


def test_quantize_leaf_dead_channel_and_nbytes():
    x = np.zeros((16, 4), np.float32)
    x[:, 1] = 3.0
    leaf = Q.quantize_leaf(x)
    assert leaf.scale[0] == 1.0 and (leaf.q[:, 0] == 0).all()
    assert leaf.nbytes == 16 * 4 * 1 + 4 * 4


def test_stacked_quantleaf_stage_slicing():
    """The transformer idiom: tree.map(lambda a: a[i]) over a stacked
    QuantLeaf must equal quantizing the slice directly — the leading
    stack axis keeps per-entry scales by construction."""
    rng = np.random.RandomState(1)
    x = rng.randn(3, 16, 8).astype(np.float32)
    stacked = Q.quantize_leaf(x)
    sliced = jax.tree.map(lambda a: a[1], stacked,
                          is_leaf=lambda n: False)
    direct = Q.quantize_leaf(x[1])
    np.testing.assert_array_equal(np.asarray(sliced.q),
                                  np.asarray(direct.q))
    np.testing.assert_array_equal(np.asarray(sliced.scale),
                                  np.asarray(direct.scale))


def test_quantize_tree_modes_and_keys():
    params = _params()
    bf = Q.quantize_tree(params, 'bf16')
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(bf))
    q8 = _lm_int8(params)
    # matmul leaves quantized, norm scales/biases stay plain bf16
    assert isinstance(q8['embed'], Q.QuantLeaf)
    assert isinstance(q8['head'], Q.QuantLeaf)
    assert isinstance(q8['stages']['wq'], Q.QuantLeaf)
    assert not isinstance(q8['stages']['ln1_scale'], Q.QuantLeaf)
    assert q8['stages']['ln1_scale'].dtype == jnp.bfloat16
    assert Q.quantize_tree(params, 'f32') is params
    with pytest.raises(ValueError):
        Q.parse_serve_dtype('fp8')
    assert Q.parse_serve_dtype('float32') == 'f32'


def test_tree_nbytes_reduction_ratios():
    params = _params()
    f32 = Q.tree_nbytes(params)
    assert Q.tree_nbytes(Q.quantize_tree(params, 'bf16')) * 2 == f32
    assert Q.tree_nbytes(_lm_int8(params)) * 3 < f32  # > 3x smaller


# --- qdot: the W8A8 leg ------------------------------------------------------

def test_qdot_plain_array_is_native_matmul():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(5, 16), jnp.float32)
    w = jnp.asarray(rng.randn(16, 8), jnp.float32)
    np.testing.assert_array_equal(np.asarray(Q.qdot(x, w)),
                                  np.asarray(x @ w))


def test_int8_matmul_pallas_bitwise_equals_xla():
    """Exact integer accumulation: the MXU-tiled kernel (interpret=True
    on CPU) and lax.dot_general agree BITWISE — ragged shapes exercise
    the padding."""
    rng = np.random.RandomState(3)
    for m, k, n in ((5, 33, 17), (128, 256, 128), (1, 7, 300)):
        a = rng.randint(-127, 128, (m, k)).astype(np.int8)
        b = rng.randint(-127, 128, (k, n)).astype(np.int8)
        ref = jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        out = PK.pallas_int8_matmul(jnp.asarray(a), jnp.asarray(b))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_qdot_quantized_pallas_mode_invariant(monkeypatch):
    """serve.dtype=int8 outputs are a pure function of the int8 weights:
    identical with CXXNET_PALLAS unset (XLA int8 dot) and =1 (Pallas
    kernel, interpret on CPU)."""
    if PK.pltpu is None:
        pytest.skip('pallas TPU memory spaces unavailable')
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(6, 32), jnp.bfloat16)
    w = Q.quantize_leaf(rng.randn(32, 24).astype(np.float32),
                        out_dtype=jnp.bfloat16)
    monkeypatch.delenv('CXXNET_PALLAS', raising=False)
    xla = np.asarray(Q.qdot(x, w), np.float32)
    monkeypatch.setenv('CXXNET_PALLAS', '1')
    pallas = np.asarray(Q.qdot(x, w), np.float32)
    np.testing.assert_array_equal(xla, pallas)


# --- DecodeEngine tiers ------------------------------------------------------

class TestDecodeTiers:
    def _streams(self, dtype, prompts, temps, keys, flash=0):
        eng = DecodeEngine(_params(), CFG, slots=4, pages=64, page_size=8,
                           max_prompt=16, max_new_bound=32, dtype=dtype,
                           flash_decode=flash)
        try:
            reqs = [eng.submit_direct(p, max_new=10, temperature=tp,
                                      rng=k)
                    for p, tp, k in zip(prompts, temps, keys)]
            outs = []
            for r in reqs:
                assert r.event.wait(60) and r.error is None, r.error
                outs.append(np.asarray(r.result))
            ref, cfg = eng.params, eng.cfg
            resident = eng.resident_bytes()
        finally:
            eng.close(30)
        return outs, ref, cfg, resident

    def test_exact_stream_twins_every_tier(self):
        """EVERY serve.dtype tier keeps the bitwise-twin discipline: the
        engine's streams equal generate() over its own stored tree and
        compute config — greedy and sampled, gather and flash legs."""
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, 64, (1, int(rng.randint(1, 12))))
                   .astype(np.int32) for _ in range(4)]
        temps = [0.0, 0.0, 0.8, 1.2]
        keys = [None, None, jax.random.PRNGKey(9), jax.random.PRNGKey(10)]
        residents = {}
        for dtype in ('f32', 'bf16', 'int8'):
            for flash in (0, 1):
                outs, ref, cfg, resident = self._streams(
                    dtype, prompts, temps, keys, flash=flash)
                for o, p, tp, k in zip(outs, prompts, temps, keys):
                    off = np.asarray(T.generate(ref, p, 10, cfg,
                                                temperature=tp,
                                                rng=k))[0]
                    np.testing.assert_array_equal(o, off)
                residents[dtype] = resident
        # resident-byte ladder: bf16 halves params+pool; int8 shrinks
        # further (params ~4x; the bf16 pool shares the ledger)
        assert residents['bf16'] < residents['f32'] * 0.55
        assert residents['int8'] < residents['bf16']

    def test_int8_tolerance_twin_vs_f32(self):
        """PINNED tolerance vs the f32 model (never silently looser):
        prefill logits within 5% relative, top-1 equal, and the greedy
        stream agrees with f32's on a majority prefix — all
        deterministic on this fixed seed."""
        params = _params()
        rng = np.random.RandomState(6)
        prompt = rng.randint(0, 64, (1, 7)).astype(np.int32)
        q8 = _lm_int8(params)
        _, _, l32 = jax.jit(lambda p, t: T.prefill_kv(p, t, jnp.int32(0),
                                                      CFG))(params, prompt)
        _, _, l8 = jax.jit(lambda p, t: T.prefill_kv(p, t, jnp.int32(0),
                                                     CFG_BF16))(q8, prompt)
        l32, l8 = np.asarray(l32), np.asarray(l8)
        rel = np.abs(l8 - l32).max() / np.abs(l32).max()
        assert rel < 0.05, f'int8 prefill logits drifted: rel={rel}'
        assert (l8.argmax(-1) == l32.argmax(-1)).all()
        s32 = np.asarray(T.generate(params, prompt, 12, CFG))[0]
        s8 = np.asarray(T.generate(q8, prompt, 12, CFG_BF16))[0]
        agree = (s32 == s8).mean()
        assert s32[0] == s8[0]
        assert agree >= 0.5, f'int8 greedy stream agreement {agree}'

    def test_bf16_tolerance_twin_vs_f32(self):
        params = _params()
        rng = np.random.RandomState(7)
        prompt = rng.randint(0, 64, (1, 9)).astype(np.int32)
        p16 = Q.quantize_tree(params, 'bf16')
        _, _, l32 = jax.jit(lambda p, t: T.prefill_kv(p, t, jnp.int32(0),
                                                      CFG))(params, prompt)
        _, _, l16 = jax.jit(lambda p, t: T.prefill_kv(p, t, jnp.int32(0),
                                                      CFG_BF16))(p16,
                                                                 prompt)
        l32, l16 = np.asarray(l32), np.asarray(l16)
        rel = np.abs(l16 - l32).max() / np.abs(l32).max()
        assert rel < 0.02, f'bf16 prefill logits drifted: rel={rel}'
        assert (l16.argmax(-1) == l32.argmax(-1)).all()

    def test_quantized_hot_swap_keeps_twin(self):
        """swap_params takes the HOST f32 tree (what .lm files carry),
        re-quantizes at swap time, and the post-swap streams twin the
        new quantized tree."""
        eng = DecodeEngine(_params(0), CFG, slots=2, pages=32,
                           page_size=8, max_prompt=16, max_new_bound=16,
                           dtype='int8')
        try:
            new_host = _params(1)
            eng.swap_params(new_host, version=1)
            rng = np.random.RandomState(8)
            p = rng.randint(0, 64, (1, 5)).astype(np.int32)
            r = eng.submit_direct(p, max_new=6)
            assert r.event.wait(60) and r.error is None
            off = np.asarray(T.generate(eng.params, p, 6, eng.cfg))[0]
            np.testing.assert_array_equal(np.asarray(r.result), off)
            assert eng.version == 1
        finally:
            eng.close(30)

    def test_budgeter_fits_more_int8_models(self):
        """The point of the tier: under a budget sized for ONE f32
        decode engine, two int8 engines fit where two f32 ones cannot
        (resident_bytes reports the true quantized footprint)."""
        from cxxnet_tpu.runtime.faults import MemoryBudgetExceededError
        from cxxnet_tpu.serve.registry import MultiModelRegistry

        def factory(dtype):
            return lambda: DecodeEngine(
                _params(), CFG, slots=2, pages=32, page_size=8,
                max_prompt=16, max_new_bound=16, dtype=dtype)

        probe = factory('f32')()
        budget = int(probe.resident_bytes() * 1.2)
        probe.close(30)

        fleet = MultiModelRegistry(mem_budget=budget)
        fleet.add_model('a8', factory('int8'))
        fleet.add_model('b8', factory('int8'))
        try:
            fleet.get('a8')
            fleet.get('b8')
            assert sorted(fleet.loaded()) == ['a8', 'b8']
        finally:
            fleet.close(10)

        fleet32 = MultiModelRegistry(mem_budget=budget)
        fleet32.add_model('a32', factory('f32'), pinned=True)
        fleet32.add_model('b32', factory('f32'))
        try:
            fleet32.get('a32')
            with pytest.raises(MemoryBudgetExceededError):
                fleet32.get('b32')
        finally:
            fleet32.close(10)


# --- PredictEngine tiers -----------------------------------------------------

class TestPredictTiers:
    @pytest.fixture()
    def nets(self):
        from tests.test_serve import make_net
        return make_net

    def _host(self, engine):
        return jax.tree.map(lambda x: np.asarray(x), engine.params)

    def test_exact_and_tolerance_twins(self, nets):
        """Bucket-ladder scores on every tier: bitwise-equal to an f32
        engine fed the dequantized tree (exact twin), and within PINNED
        bounds of the original f32 scores with full top-1 agreement
        (tolerance twin).  The request spans the ladder (pad + chunk)."""
        e32 = PredictEngine(nets(seed=3)._trainer, (1, 4))
        host = self._host(e32)
        rng = np.random.RandomState(9)
        data = rng.randn(11, 1, 1, 8).astype(np.float32)  # chunks + pad
        s32 = e32.predict_scores(data)
        bounds = {'bf16': 1e-4, 'int8': 1e-3}
        for dtype in ('bf16', 'int8'):
            eq = PredictEngine(nets(seed=3)._trainer, (1, 4), dtype=dtype)
            assert eq.compile_count == 0
            sq = eq.predict_scores(data)
            # exact twin: f32 engine over the dequantized tree
            et = PredictEngine(nets(seed=3)._trainer, (1, 4))
            deq = Q.dequantize_tree(Q.quantize_tree(host, dtype),
                                    jnp.float32)
            et.swap_params(jax.tree.map(lambda x: np.asarray(x), deq))
            np.testing.assert_array_equal(sq, et.predict_scores(data))
            # tolerance twin: pinned bound, never silently looser
            diff = float(np.abs(sq - s32).max())
            assert diff < bounds[dtype], (dtype, diff)
            assert (sq.argmax(-1) == s32.argmax(-1)).all()
            # resident ledger: bf16 halves; int8 beats bf16 even on this
            # toy net where biases/scales dominate (the >=3x param claim
            # is pinned on the transformer tree + the bench receipt)
            if dtype == 'bf16':
                assert eq.resident_bytes() * 2 == e32.resident_bytes()
            else:
                assert eq.resident_bytes() * 2 < e32.resident_bytes()

    def test_quantized_swap_through_registry_sequence(self, nets):
        """The registry's place -> warm -> swap sequence on a quantized
        engine: host f32 tree in, quantized tier served out, and the
        re-passed placed tree short-circuits cleanly."""
        eq = PredictEngine(nets(seed=3)._trainer, (1, 4), dtype='int8')
        donor = PredictEngine(nets(seed=5)._trainer, (1, 4))
        host = self._host(donor)
        placed = eq.place_params(host)
        eq.warm_params(placed)
        eq.swap_params(placed, version=7)
        assert eq.version == 7
        rng = np.random.RandomState(10)
        data = rng.randn(3, 1, 1, 8).astype(np.float32)
        et = PredictEngine(nets(seed=0)._trainer, (1, 4))
        deq = Q.dequantize_tree(Q.quantize_tree(host, 'int8'),
                                jnp.float32)
        et.swap_params(jax.tree.map(lambda x: np.asarray(x), deq))
        np.testing.assert_array_equal(eq.predict_scores(data),
                                      et.predict_scores(data))

    def test_swap_rejects_structure_change(self, nets):
        eq = PredictEngine(nets(seed=3)._trainer, (1, 4), dtype='int8')
        bad = self._host(eq)        # QUANTIZED structure != f32 contract
        bad = jax.tree.map(lambda x: x, bad)
        with pytest.raises(ValueError, match='structure'):
            # a half-tree is neither the f32 contract nor our own output
            eq.swap_params({'nope': np.zeros((2, 2), np.float32)})


# --- wrapper / C-ABI keys ----------------------------------------------------

def test_capi_serve_start_parses_dtype():
    from cxxnet_tpu import capi

    class NetStub:
        def serve_start(self, **kw):
            self.kw = kw

    stub = NetStub()
    capi.net_serve_start(stub, 'buckets=1:4;dtype=int8')
    assert stub.kw['dtype'] == 'int8'
    assert stub.kw['buckets'] == '1,4'


def test_capi_lm_serve_parses_dtype_and_flash(tmp_path):
    from cxxnet_tpu import capi
    svc = capi.lm_serve_start(
        'vocab=64;d_model=32;heads=4;d_ff=48;stages=2;slots=2;pages=32;'
        'page_size=8;max_prompt=12;max_new=6;dtype=bf16;flash_decode=1')
    try:
        assert svc.engine.serve_dtype == 'bf16'
        assert svc.engine.use_flash
        assert svc.engine.cfg.dtype == jnp.bfloat16
        prompt = np.arange(5, dtype=np.int32)
        toks = capi.lm_serve_generate(svc, memoryview(prompt.tobytes()),
                                      5, 4)
        off = np.asarray(T.generate(
            svc.engine.params, prompt[None], 4, svc.engine.cfg))[0]
        np.testing.assert_array_equal(toks, off[:len(toks)])
    finally:
        capi.lm_serve_stop(svc)


def test_online_pipeline_serves_quantized_tier(tmp_path):
    """task=online reuses the serve.* keys — OnlineConfig.dtype must
    actually reach the colocated PredictEngine (the trainer+server-on-
    one-chip memory-pressure scenario is exactly what the tier is for)."""
    from cxxnet_tpu import capi
    from tests.test_online import MLP_CONF, ListIter, _make_batches

    net = capi.net_create('cpu', MLP_CONF)
    net.set_param('seed', 2)
    net.init_model()
    capi.net_online_start(
        net, ListIter(_make_batches(6, seed=2)),
        f'model_dir={tmp_path}/m;rounds=1;save_every=5;reload=0.02;'
        f'buckets=4:8;watchdog_deadline=30;dtype=int8')
    try:
        eng = net._online.engine
        assert eng.serve_dtype == 'int8'
        assert any(isinstance(l, Q.QuantLeaf)
                   for l in jax.tree.leaves(
                       eng.params,
                       is_leaf=lambda n: isinstance(n, Q.QuantLeaf)))
        rows = np.random.RandomState(0).randn(4, 1, 1, 16)\
            .astype(np.float32)
        out = capi.net_online_predict(net, memoryview(rows.tobytes()),
                                      rows.shape)
        assert out.shape == (4,)
        capi.net_online_wait(net)
    finally:
        capi.net_online_stop(net)


def test_wrapper_serve_start_dtype(tmp_path):
    from tests.test_serve import make_net
    net = make_net(seed=3)
    net.serve_start(buckets='1,4', dtype='int8', warm=False)
    try:
        assert net._engine.serve_dtype == 'int8'
        rng = np.random.RandomState(11)
        out = net.serve_scores(rng.randn(3, 1, 1, 8).astype(np.float32))
        assert out.shape[0] == 3
    finally:
        net.serve_stop()
