"""graftstorm suite: adversarial traffic scenarios + SLO-driven
autoscaling (serve/scenario.py, serve/autoscale.py; doc/serving.md
"Scenarios and autoscaling").

The load-bearing claims:

* a :class:`ScenarioSpec` is a twin of itself — the schedule and every
  prompt token are pure functions of the spec, independent of execution
  order, autoscaler actions, and wall jitter;
* every submitted request lands in exactly ONE typed terminal bucket
  and the ledger reconciles bucket-for-bucket against the service's
  single-owner counters — sustained slow-client abandonment included;
* the autoscaler is damped (a flapping verdict produces ZERO actions),
  bounded, reversible, and degrades explicitly — and shrinking the live
  page cap under live refcounted prefix pages never frees a referenced
  page;
* the fault grammar / scenario grammar / autoscale grammar documented
  in doc/ cannot drift from the registered kinds and keys.
"""

import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.models import transformer as T
from cxxnet_tpu.runtime.faults import (FaultPlan, RequestAbandonedError,
                                       ServeOverloadError)
from cxxnet_tpu.serve.autoscale import AutoscalePolicy, Autoscaler
from cxxnet_tpu.serve.decode import DecodeService
from cxxnet_tpu.serve.scenario import (SHAPES, ScenarioLedger,
                                       ScenarioSpec, drive)

pytestmark = pytest.mark.scenario

CFG = T.TransformerConfig(vocab_size=64, d_model=16, num_heads=2,
                          d_ff=32, num_stages=1, seq_len=64, attn='local')


def _params(seed: int = 0):
    return T.init_params(np.random.RandomState(seed), CFG)


def _service(**kw):
    kw.setdefault('slots', 2)
    kw.setdefault('pages', 16)
    kw.setdefault('page_size', 4)
    kw.setdefault('max_prompt', 16)
    kw.setdefault('max_new_bound', 8)
    kw.setdefault('max_queue', 8)
    kw.setdefault('max_wait', 0.001)
    kw.setdefault('deadline', 30.0)
    kw.setdefault('eos_id', None)
    return DecodeService(_params(), CFG, **kw)


def _offline(params, prompt, max_new):
    return np.asarray(T.generate(params, prompt, max_new, CFG))[0]


# --- the spec: grammar + determinism ---------------------------------------

def test_spec_parse_describe_roundtrip():
    spec = ScenarioSpec.parse('shape=flash;seed=7;requests=128;qps=300;'
                              'burst=6;abandon=0.25;patience=0.1;'
                              'max_prompt=24;max_new=12')
    assert spec.shape == 'flash' and spec.burst == 6.0
    assert ScenarioSpec.parse(spec.describe()) == spec
    # defaults round-trip too
    assert ScenarioSpec.parse(ScenarioSpec().describe()) == ScenarioSpec()


def test_spec_rejects_unknown_and_invalid():
    with pytest.raises(ValueError, match='unknown scenario option'):
        ScenarioSpec.parse('shape=steady;bogus=1')
    with pytest.raises(ValueError, match='unknown scenario shape'):
        ScenarioSpec.parse('shape=tsunami')
    with pytest.raises(ValueError, match='requests > 0'):
        ScenarioSpec.parse('requests=0')
    with pytest.raises(ValueError, match='probability'):
        ScenarioSpec.parse('abandon=1.5')


def test_schedule_is_a_twin_of_itself():
    spec = ScenarioSpec.parse('shape=heavy_tail;seed=11;requests=64;'
                              'qps=500;tail=1.1;abandon=0.3')
    a, b = spec.schedule(), spec.schedule()
    assert a == b
    # and prompt contents replay bit for bit
    for rec in a[:8]:
        p1 = spec.prompt_for(rec.index, rec.prompt_len, CFG.vocab_size)
        p2 = spec.prompt_for(rec.index, rec.prompt_len, CFG.vocab_size)
        assert (p1 == p2).all() and p1.dtype == np.int32
    # a different seed is a different storm
    other = ScenarioSpec.parse('shape=heavy_tail;seed=12;requests=64;'
                               'qps=500;tail=1.1;abandon=0.3')
    assert other.schedule() != a


def test_prompt_content_is_execution_order_independent():
    """Prompt tokens are keyed by request INDEX, not arrival/execution
    order — the property that lets autoscaler actions and batch
    composition reorder execution without changing a single token."""
    spec = ScenarioSpec.parse('seed=3;requests=16;qps=100')
    sched = spec.schedule()
    forward = [spec.prompt_for(r.index, r.prompt_len, CFG.vocab_size)
               for r in sched]
    backward = [spec.prompt_for(r.index, r.prompt_len, CFG.vocab_size)
                for r in reversed(sched)]
    for f, b in zip(forward, reversed(backward)):
        assert (f == b).all()


def test_shapes_produce_their_curves():
    n = 90
    flash = ScenarioSpec.parse(f'shape=flash;requests={n};qps=100;burst=10')
    gaps = np.diff([r.t_offset for r in flash.schedule()])
    third = n // 3
    # the middle third arrives 10x faster than the edges
    assert np.mean(gaps[third:2 * third - 1]) < np.mean(gaps[:third]) / 5
    diurnal = ScenarioSpec.parse(f'shape=diurnal;requests={n};qps=100')
    dgaps = np.diff([r.t_offset for r in diurnal.schedule()])
    assert dgaps.max() > 2.5 * dgaps.min()        # trough vs peak
    heavy = ScenarioSpec.parse(f'shape=heavy_tail;requests={n};qps=100;'
                               'tail=1.05;max_prompt=32')
    lens = [r.prompt_len for r in heavy.schedule()]
    assert max(lens) == 32 and sorted(lens)[n // 2] < 16  # tail + mass
    tenants = ScenarioSpec.parse(f'shape=tenants;requests={n};qps=100;'
                                 'tenants=3')
    assert [r.tenant for r in tenants.schedule()[:6]] == [0, 1, 2, 0, 1, 2]
    assert 'steady' in SHAPES


def test_abandonment_is_seeded_and_bounded():
    spec = ScenarioSpec.parse('seed=5;requests=200;qps=1000;abandon=0.4;'
                              'patience=0.01')
    sched = spec.schedule()
    quitters = [r for r in sched if r.abandon_after is not None]
    assert 40 <= len(quitters) <= 120           # ~0.4 of 200, seeded
    assert all(q.abandon_after > 0 for q in quitters)
    assert [r.index for r in spec.schedule()
            if r.abandon_after is not None] == [q.index for q in quitters]


# --- the ledger ------------------------------------------------------------

def test_ledger_total_and_reconcile_catch_drops():
    led = ScenarioLedger()
    led.note_submit()
    led.note_submit()
    led.note('served', latency=0.01, index=0, stream=[1, 2])
    with pytest.raises(AssertionError, match='drop/double-count'):
        led.reconcile()
    led.note('rejected')
    led.reconcile()                              # balanced now
    assert led.total() == 2 and led.shed() == 1
    s = led.summary()
    assert s['submitted'] == 2 and s['served'] == 1 and s['p99_s'] > 0


# --- live service: abandonment + reconciliation (satellite 1) --------------

def test_sustained_abandonment_reconciles_exactly():
    """The hardened slow-client path: under sustained abandonment every
    request still lands in exactly one typed bucket, and the ledger
    agrees with the service's single-owner counters bucket for bucket
    (abandoned+served+shed == submitted, no drops, no double counts)."""
    svc = _service()
    try:
        spec = ScenarioSpec.parse('shape=steady;seed=13;requests=40;'
                                  'qps=400;abandon=0.5;patience=0.005;'
                                  'max_prompt=10;max_new=6')
        led = drive(svc, spec, vocab=CFG.vocab_size)
        led.reconcile(svc.engine.stats)
        s = led.summary()
        assert s['submitted'] == 40
        assert s['served'] + led.shed() + s['abandoned'] == 40
        # the storm actually exercised the path under test
        assert s['abandoned'] > 0, s
        assert int(svc.engine.stats.get('abandoned')) == s['abandoned']
    finally:
        svc.close(30.0)


def test_scenario_streams_twin_offline_generate():
    """Bitwise stream twins under scenario traffic: every SERVED stream
    equals the offline generate call for its (index-keyed) prompt."""
    svc = _service(max_queue=32)
    try:
        # absorb the first-dispatch compile before pacing the storm —
        # this test asserts the no-pressure outcome (every request
        # served), so compile latency must not masquerade as overload
        svc.generate(np.zeros((1, 2), np.int32), 2)
        spec = ScenarioSpec.parse('shape=heavy_tail;seed=21;requests=12;'
                                  'qps=200;tail=1.2;max_prompt=10;'
                                  'max_new=6')
        sched = spec.schedule()
        base = ScenarioLedger.stat_snapshot(svc.engine.stats)
        led = drive(svc, spec, vocab=CFG.vocab_size)
        led.reconcile(svc.engine.stats, base=base)
        assert led.counts['served'] == 12     # no pressure: all served
        for rec in sched:
            prompt = spec.prompt_for(rec.index, rec.prompt_len,
                                     CFG.vocab_size)
            off = _offline(svc.engine.params, prompt, rec.max_new)
            got = np.asarray(led.streams[rec.index])
            assert (got == off[:len(got)]).all(), rec.index
    finally:
        svc.close(30.0)


# --- the autoscaler (satellite 3) ------------------------------------------

class _FakeEngine:
    slots, n_pages = 8, 33

    def __init__(self):
        self.calls = []

    def live_limits(self):
        return (2, 4)

    def set_live_limits(self, max_slots=None, max_pages=None):
        self.calls.append((max_slots, max_pages))
        return (max_slots, max_pages)

    def capacity_view(self):
        return {'slots': self.slots}


class _FakeBatcher:
    max_queue = 16

    def set_max_queue(self, n):
        prev, self.max_queue = self.max_queue, int(n)
        return prev


def _scaler(verdict_box, policy='min_slots=1;min_pages=1;min_queue=2;'
                               'max_queue=64;cooldown=0;hysteresis=2;'
                               'step=2'):
    pol = AutoscalePolicy.parse(policy)
    sc = Autoscaler(pol, verdicts=lambda: {'o': {'state': verdict_box[0]}},
                    gauges=lambda: {})
    eng, bat = _FakeEngine(), _FakeBatcher()
    sc.bind_engine(eng)
    sc.bind_batcher(bat)
    return sc, eng, bat


def test_policy_parse_describe_roundtrip_and_validation():
    pol = AutoscalePolicy.parse('min_slots=2;max_slots=16;cooldown=0.5;'
                                'hysteresis=3;step=2;interval=0')
    assert AutoscalePolicy.parse(pol.describe()) == pol
    for bad in ('bogus=1', 'step=1.0', 'hysteresis=0', 'min_slots=0',
                'min_pages=5;max_pages=2'):
        with pytest.raises(ValueError):
            AutoscalePolicy.parse(bad)


def test_flapping_verdict_produces_zero_actions():
    """Hysteresis: an OK<->AT_RISK flap at a burn-rate boundary never
    accumulates enough same-direction agreement to act."""
    box = ['OK']
    sc, eng, bat = _scaler(box)
    before = (dict(sc.knob_values()), bat.max_queue, list(eng.calls))
    for i in range(50):
        box[0] = 'AT_RISK' if i % 2 else 'OK'
        assert sc.evaluate(now=float(i)) == []
    assert sc.history() == []
    assert (dict(sc.knob_values()), bat.max_queue,
            list(eng.calls)) == before


def test_sustained_pressure_grows_and_ok_reverts_to_baseline():
    box = ['AT_RISK']
    sc, eng, bat = _scaler(box)
    base = dict(sc.knob_values())
    for i in range(8):
        sc.evaluate(now=float(i))
    grown = sc.knob_values()
    assert grown['slots'] == 8 and grown['pages'] == 32
    assert bat.max_queue == grown['queue'] > base['queue']
    box[0] = 'OK'
    for i in range(8, 30):
        sc.evaluate(now=float(i))
    assert sc.knob_values() == base              # reversible, to baseline
    assert bat.max_queue == base['queue']


def test_cooldown_rate_limits_actions():
    box = ['AT_RISK']
    sc, eng, _ = _scaler(box, policy='cooldown=100;hysteresis=1;step=2')
    sc.evaluate(now=0.0)
    n = len(sc.history())
    assert n > 0
    for t in (1.0, 2.0, 50.0):                   # inside the cooldown
        sc.evaluate(now=t)
    assert len(sc.history()) == n
    sc.evaluate(now=101.0)                       # past it
    assert len(sc.history()) > n


def test_breach_at_ceiling_degrades_explicitly_then_recovers():
    box = ['BREACHED']
    sc, eng, bat = _scaler(box)
    for i in range(12):
        sc.evaluate(now=float(i))
    assert sc.degraded
    assert bat.max_queue == 2                    # clamped to the floor
    assert any(a['kind'] == 'degrade' for a in sc.history())
    # degraded state holds under continued pressure (no re-open flap)
    for i in range(12, 16):
        sc.evaluate(now=float(i))
    assert bat.max_queue == 2
    box[0] = 'OK'
    for i in range(16, 30):
        sc.evaluate(now=float(i))
    assert not sc.degraded and bat.max_queue == 16
    assert any(a['kind'] == 'recover' for a in sc.history())


def test_autoscaler_interval_thread_named_and_joined():
    pol = AutoscalePolicy.parse('interval=0.01;hysteresis=2')
    sc = Autoscaler(pol, verdicts=lambda: {}, gauges=lambda: {},
                    name='t1')
    try:
        names = [t.name for t in threading.enumerate()]
        assert 'cxxnet-scale-t1' in names
        time.sleep(0.05)
    finally:
        sc.close()
    assert 'cxxnet-scale-t1' not in [t.name for t in threading.enumerate()
                                     if t.is_alive()]


# --- live caps on the real engine (satellite 3) ----------------------------

def test_live_cap_shrink_never_frees_referenced_prefix_page():
    """Shrinking the live page cap under live refcounted prefix pages
    is an ADMISSION change only: pages referenced by the index or an
    in-flight stream stay exactly where they are (no page is ever both
    free and referenced), streams stay bitwise twins, and a request
    that no longer fits sheds typed instead of waiting forever."""
    svc = _service(slots=2, pages=16, page_size=4, prefix_share=8)
    eng = svc.engine
    try:
        shared = np.arange(8, dtype=np.int32)[None] % CFG.vocab_size
        # populate the prefix index (first request publishes its pages)
        first = svc.generate(shared, 4)
        with eng._cond:
            indexed = {e['page'] for e in eng._prefix.values()}
            assert indexed, 'prefix index should hold pages'
        # shrink the live cap to exactly what an aligned prefix-hit
        # request needs; the physical pool is untouched
        eng.set_live_limits(max_pages=4)
        assert eng.live_limits()[1] == 4
        with eng._cond:
            free = set(eng._free_pages)
            refs = {p for p in range(1, eng.n_pages)
                    if eng._page_refs[p] > 0}
            assert not (free & refs), 'a referenced page is on the free list'
            assert indexed <= refs, 'shrink dropped an index reference'
        # a too-big request sheds typed immediately (cap, not pool)
        big = np.arange(14, dtype=np.int32)[None] % CFG.vocab_size
        from cxxnet_tpu.runtime.faults import DecodeSlotsExhaustedError
        with pytest.raises(DecodeSlotsExhaustedError, match='live page cap'):
            svc.generate(big, 4)
        # the prefix-sharing request still fits under the shrunk cap and
        # its stream still equals the unshrunk twin
        again = svc.generate(shared, 4)
        assert (np.asarray(again) == np.asarray(first)).all()
        with eng._cond:
            free = set(eng._free_pages)
            refs = {p for p in range(1, eng.n_pages)
                    if eng._page_refs[p] > 0}
            assert not (free & refs)
        # restore: the clamp is reversible
        eng.set_live_limits(max_pages=eng.n_pages - 1)
        assert np.asarray(svc.generate(big, 4)).shape == (4,)
    finally:
        svc.close(30.0)


def test_live_slot_cap_clamps_admission_not_inflight():
    svc = _service(slots=4)
    eng = svc.engine
    try:
        eng.set_live_limits(max_slots=1)
        assert eng.live_limits()[0] == 1
        cv = eng.capacity_view()
        assert cv['live_slot_cap'] == 1 and cv['slots'] == 4
        p = np.arange(6, dtype=np.int32)[None] % CFG.vocab_size
        # serially the clamp is invisible: requests run one at a time
        outs = [svc.generate(p, 4) for _ in range(3)]
        assert all((np.asarray(o) == np.asarray(outs[0])).all()
                   for o in outs)
        # out-of-range clamps are pinned to [1, physical]
        assert eng.set_live_limits(max_slots=99)[0] == 4
        assert eng.set_live_limits(max_slots=0)[0] == 1
    finally:
        svc.close(30.0)


def test_autoscaler_on_real_engine_under_flash_crowd():
    """The composed loop: a flash-crowd scenario over a deliberately
    tight engine, with the autoscaler fed a pressure verdict — caps
    grow toward the physical ceiling while streams stay twins and the
    ledger reconciles."""
    svc = _service(slots=2, pages=16, max_queue=16)
    eng = svc.engine
    try:
        eng.set_live_limits(max_slots=1, max_pages=4)
        pol = AutoscalePolicy.parse('min_slots=1;min_pages=2;min_queue=2;'
                                    'cooldown=0;hysteresis=2;step=2')
        sc = Autoscaler(
            pol,
            verdicts=lambda: {'load': {'state': 'AT_RISK'}},
            gauges=lambda: {})
        sc.bind_engine(eng)
        sc.bind_batcher(svc.batcher)
        spec = ScenarioSpec.parse('shape=flash;seed=17;requests=20;'
                                  'qps=300;burst=8;max_prompt=8;'
                                  'max_new=4')
        led = drive(svc, spec, vocab=CFG.vocab_size,
                    on_tick=lambda _t: sc.evaluate())
        led.reconcile(svc.engine.stats)
        slots_cap, pages_cap = eng.live_limits()
        assert slots_cap == 2 and pages_cap == 15   # grew to physical
        assert led.counts['served'] > 0
        for idx, stream in led.streams.items():
            rec = spec.schedule()[idx]
            prompt = spec.prompt_for(idx, rec.prompt_len, CFG.vocab_size)
            off = _offline(eng.params, prompt, rec.max_new)
            got = np.asarray(stream)
            assert (got == off[:len(got)]).all(), idx
    finally:
        svc.close(30.0)


# --- doc drift (satellite 2) -----------------------------------------------

def _repo_doc(rel):
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(here, 'doc', rel)) as f:
        return f.read()


def test_fault_grammar_table_matches_registered_kinds():
    """doc/fault_tolerance.md's fault-grammar table and
    FaultPlan.registered_kinds() cannot drift: every registered kind is
    documented, every documented event is registered."""
    from cxxnet_tpu.analysis.config_keys import backtick_key, doc_table_rows
    text = _repo_doc('fault_tolerance.md')
    rows = doc_table_rows(text, after='## Fault-injection harness')
    documented = {backtick_key(r[0]) for r in rows
                  if backtick_key(r[0]) is not None}
    registered = set(FaultPlan.registered_kinds())
    assert documented == registered, (
        f'doc minus code: {sorted(documented - registered)}, '
        f'code minus doc: {sorted(registered - documented)}')
    assert 'slow_step' in registered


def test_scenario_and_autoscale_tables_match_registered_keys():
    from cxxnet_tpu.analysis.config_keys import backtick_key, doc_table_rows
    text = _repo_doc('serving.md')
    scen_heading = '### Scenario grammar'
    auto_heading = '### Autoscale policy grammar'
    assert scen_heading in text and auto_heading in text
    auto_rows = doc_table_rows(text, after=auto_heading)
    scen_all = doc_table_rows(text, after=scen_heading)
    scen_rows = scen_all[:len(scen_all) - len(auto_rows)]

    def keys(rows):
        return {backtick_key(r[0]) for r in rows
                if backtick_key(r[0]) is not None and r[0] != 'key'}

    assert keys(scen_rows) == set(ScenarioSpec.registered_keys()), (
        keys(scen_rows) ^ set(ScenarioSpec.registered_keys()))
    assert keys(auto_rows) == set(AutoscalePolicy.registered_keys()), (
        keys(auto_rows) ^ set(AutoscalePolicy.registered_keys()))


def test_new_cli_keys_are_documented():
    """serve.scenario / serve.autoscale ride the config-key-drift lint's
    contract: parsed in main.py, backticked in a DOC_FILE."""
    from cxxnet_tpu.analysis.config_keys import doc_keys
    documented = doc_keys(_repo_doc('tasks.md'))
    assert {'serve.scenario', 'serve.autoscale'} <= documented


# --- the composed chaos drill ----------------------------------------------

def test_chaos_flash_crowd_with_slow_step_faultplan():
    """The ISSUE's composed drill, test-sized: a slow_step@every
    FaultPlan (deterministic compute stalls between token boundaries)
    composed with a flash-crowd scenario in ONE run — zero twin
    violations, every non-served outcome typed."""
    from cxxnet_tpu.runtime import faults
    plan = FaultPlan.parse('seed=1;slow_step@every=3:0.002')
    svc = _service(slots=2, pages=16)
    prev = faults.install_plan(plan)
    try:
        spec = ScenarioSpec.parse('shape=flash;seed=29;requests=16;'
                                  'qps=300;burst=6;max_prompt=8;'
                                  'max_new=4')
        led = drive(svc, spec, vocab=CFG.vocab_size)
        faults.install_plan(prev)
        led.reconcile(svc.engine.stats)
        assert any(tag.startswith('slow_step@every=')
                   for tag in plan.fired()), plan.fired()
        assert led.counts['served'] > 0
        # zero twin violations under the composed storm
        for idx, stream in led.streams.items():
            rec = spec.schedule()[idx]
            prompt = spec.prompt_for(idx, rec.prompt_len, CFG.vocab_size)
            off = _offline(svc.engine.params, prompt, rec.max_new)
            got = np.asarray(stream)
            assert (got == off[:len(got)]).all(), idx
        # only typed outcomes: the ledger has no untyped bucket at all,
        # and reconcile already proved nothing fell outside it
        assert led.total() == led.summary()['submitted'] == 16
    finally:
        faults.install_plan(prev)
        svc.close(30.0)


def test_fault_plan_slow_step_parse_describe_roundtrip():
    plan = FaultPlan.parse('seed=4;slow_step=2:0.01;slow_step@every=5:0.02')
    desc = plan.describe()
    assert 'slow_step=2:0.01' in desc and 'slow_step@every=5:0.02' in desc
    plan2 = FaultPlan.parse(desc)
    assert plan2.describe() == desc
