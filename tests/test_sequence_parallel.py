"""Ring attention / Ulysses correctness vs single-device attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from cxxnet_tpu.parallel.sequence import (attention_reference, ring_attention,
                                          ulysses_attention)


def make_qkv(b=2, s=32, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    return mk(), mk(), mk()


def make_mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ('data',))


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('n_dev', [4, 8])
def test_ring_attention_matches_reference(n_dev, causal):
    q, k, v = make_qkv()
    mesh = make_mesh(n_dev)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_ulysses_matches_reference(causal):
    q, k, v = make_qkv(h=8)
    mesh = make_mesh(4)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grad_flows():
    q, k, v = make_qkv(s=16)
    mesh = make_mesh(4)

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    g = jax.grad(loss)(q, k, v)
    ref_g = jax.grad(lambda q, k, v: jnp.sum(
        attention_reference(q, k, v) ** 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref_g),
                               rtol=2e-3, atol=2e-4)
