"""Online inference serving suite (doc/serving.md): bucketed engine,
dynamic micro-batcher, checkpoint hot-reload registry, and the serving
satellites (bounded predict compile cache, streaming ABI iter paths,
re-entrant pipeline shutdown, tail-batch predict semantics).

CPU-only, no network: clients are in-process threads driving the real
batcher worker; determinism comes from blocking fake engines where the
real one would race.
"""

import os
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu import capi, wrapper
from cxxnet_tpu.nnet import checkpoint
from cxxnet_tpu.runtime.faults import (DeadlineExceededError,
                                       ServeError, ServeOverloadError)
from cxxnet_tpu.serve import (DynamicBatcher, ModelRegistry, PredictEngine,
                              load_model_params)
from cxxnet_tpu.utils import bucketing
from cxxnet_tpu.utils.metric import StatSet
from tests.test_io import make_img_dataset, write_mnist

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NET_CFG = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 16
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 4
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
dev = cpu
eta = 0.1
momentum = 0.9
"""


def make_net(seed=0):
    net = wrapper.Net(dev='cpu', cfg=NET_CFG)
    net.set_param('seed', seed)
    net.init_model()
    return net


def rig_constant_class(net, cls=2):
    """Zero fc2 and bias one logit so every input argmaxes to ``cls`` —
    a recognizable 'new checkpoint' for hot-reload assertions."""
    w = net.get_weight('fc2', 'wmat')
    net.set_weight(np.zeros_like(w), 'fc2', 'wmat')
    b = np.zeros(4, np.float32)
    b[cls] = 5.0
    net.set_weight(b, 'fc2', 'bias')
    return net


# --- bucketing helpers ----------------------------------------------------

def test_parse_buckets_forms():
    assert bucketing.parse_buckets('1,8,32') == (1, 8, 32)
    assert bucketing.parse_buckets('32, 8, 1, 8') == (1, 8, 32)
    with pytest.raises(ValueError):
        bucketing.parse_buckets('0,4')
    with pytest.raises(ValueError):
        bucketing.parse_buckets('')


def test_bucket_for_and_chunk_plan():
    bks = (1, 8, 32)
    assert bucketing.bucket_for(1, bks) == 1
    assert bucketing.bucket_for(2, bks) == 8
    assert bucketing.bucket_for(32, bks) == 32
    assert bucketing.bucket_for(33, bks) is None
    assert bucketing.chunk_plan(0, bks) == []
    assert bucketing.chunk_plan(5, bks) == [(0, 5, 8)]
    assert bucketing.chunk_plan(32, bks) == [(0, 32, 32)]
    # oversize splits into max-bucket chunks + smallest-fitting tail
    assert bucketing.chunk_plan(70, bks) == [(0, 32, 32), (32, 32, 32),
                                             (64, 6, 8)]
    # plans cover exactly n rows with only ladder shapes
    for n in range(1, 100):
        plan = bucketing.chunk_plan(n, bks)
        assert sum(t for _, t, _ in plan) == n
        assert all(b in bks and t <= b for _, t, b in plan)


def test_pad_rows_preserves_dtype():
    a = np.arange(6, dtype=np.uint8).reshape(2, 3)
    p = bucketing.pad_rows(a, 5)
    assert p.shape == (5, 3) and p.dtype == np.uint8
    assert np.array_equal(p[:2], a) and not p[2:].any()
    assert bucketing.pad_rows(a, 2) is a
    with pytest.raises(ValueError):
        bucketing.pad_rows(a, 1)


def test_statset_counters_and_quantiles():
    s = StatSet()
    s.inc('req')
    s.inc('req', 2)
    s.peak('depth', 3)
    s.peak('depth', 1)
    s.gauge('rate', 7.5)
    for v in range(1, 101):
        s.observe('lat', float(v))
    assert s.get('req') == 3 and s.get('depth') == 3
    assert s.quantile('lat', 0.5) == pytest.approx(50.5)
    line = s.print('serve')
    assert '\tserve-req:3' in line and '\tserve-lat.p99:' in line
    assert '\tserve-rate:7.5' in line


# --- engine ---------------------------------------------------------------

def test_engine_compile_cache_bounded():
    net = make_net()
    eng = PredictEngine(net._trainer, (1, 8, 32))
    assert eng.warm() == 3
    rng = np.random.RandomState(0)
    for n in (1, 2, 3, 5, 8, 13, 21, 32, 33, 70):
        scores = eng.predict_scores(rng.randn(n, 1, 1, 8).astype(np.float32))
        assert scores.shape == (n, 4)
    # flat (n, d) views and non-f32 wire dtypes hit the same programs
    # (jit keys on dtype too — the engine normalizes at the boundary)
    assert eng.predict_scores(np.zeros((4, 8), np.uint8)).shape == (4, 4)
    assert eng.predict_scores(np.zeros((4, 1, 1, 8), np.float64)).shape \
        == (4, 4)
    # every size above hit a pre-compiled bucket program
    assert eng.compile_count == 3


def test_engine_predict_matches_trainer_predict():
    net = make_net()
    eng = PredictEngine(net._trainer, (8,))
    rng = np.random.RandomState(3)
    d = rng.randn(5, 1, 1, 8).astype(np.float32)
    np.testing.assert_array_equal(eng.predict(d), net.predict(d))


def test_engine_inference_only_state():
    net = wrapper.Net(dev='cpu', cfg=NET_CFG)
    net.set_param('inference_only', '1')
    net.init_model()
    tr = net._trainer
    assert tr.opt_state is None and tr.grad_acc is None
    d = np.zeros((4, 1, 1, 8), np.float32)
    assert net.predict(d).shape == (4,)        # forward still works
    with pytest.raises(RuntimeError, match='inference_only'):
        net.update(d, np.zeros((4, 1), np.float32))


def test_engine_swap_validates_structure():
    net = make_net()
    eng = PredictEngine(net._trainer, (8,))
    bad = {k: dict(v) for k, v in net._trainer.params.items()}
    key = next(iter(bad))
    field = next(iter(bad[key]))
    bad[key][field] = np.zeros((3, 3), np.float32)   # wrong shape
    with pytest.raises(ValueError, match='swap_params'):
        eng.swap_params(bad)
    with pytest.raises(ValueError, match='structure'):
        eng.swap_params({'nope': {}})


def test_engine_inflight_request_keeps_old_params():
    """The params snapshot is taken at request start: a swap landing while
    a request is in flight must not affect that request's result."""
    net = make_net()
    eng = PredictEngine(net._trainer, (8,))
    eng.warm()
    rng = np.random.RandomState(5)
    d = rng.randn(4, 1, 1, 8).astype(np.float32)
    want_old = eng.predict_scores(d)

    orig = eng._fwd
    entered, release = threading.Event(), threading.Event()

    def slow_fwd(params, data):
        entered.set()
        assert release.wait(10)
        return orig(params, data)

    eng._fwd = slow_fwd
    out = {}
    t = threading.Thread(target=lambda: out.setdefault(
        'scores', eng.predict_scores(d)))
    t.start()
    assert entered.wait(10)
    # swap to the constant-class rig while the request is mid-flight
    v2 = rig_constant_class(make_net(seed=9))
    eng.swap_params(v2._trainer.params, version='v2')
    release.set()
    t.join(10)
    eng._fwd = orig
    np.testing.assert_array_equal(out['scores'], want_old)
    # and the NEXT request sees the new params
    assert np.all(eng.predict(d) == 2.0)
    assert eng.swap_count == 1 and eng.version == 'v2'


def test_engine_rejects_bucket_not_dividing_mesh(tmp_path):
    """On a multi-device mesh the padded batch must shard evenly."""
    net = wrapper.Net(dev='cpu:0-7', cfg=NET_CFG)
    net.init_model()
    with pytest.raises(ValueError, match='data axis'):
        PredictEngine(net._trainer, (1, 8))
    eng = PredictEngine(net._trainer, (8, 32))    # multiples of 8: fine
    assert eng.predict_scores(np.zeros((3, 1, 1, 8), np.float32)).shape \
        == (3, 4)


# --- batcher --------------------------------------------------------------

class FakeEngine:
    """Deterministic engine stub: records executed batch sizes; optional
    gate blocks execution so queue states are controllable."""

    def __init__(self, buckets=(1, 8, 32), gate=None, fail=False):
        self.buckets = tuple(buckets)
        self.gate = gate
        self.fail = fail
        self.batches = []

    def predict_scores(self, data):
        if self.gate is not None:
            assert self.gate.wait(10)
        if self.fail:
            raise RuntimeError('engine exploded')
        self.batches.append(data.shape[0])
        return np.arange(data.shape[0], dtype=np.float32)[:, None]


def test_batcher_coalesces_concurrent_requests():
    gate = threading.Event()
    eng = FakeEngine(buckets=(1, 8, 32), gate=gate)
    # max_wait=0: coalescing below comes purely from the queue backlog
    # that builds while the worker is busy — deterministic
    b = DynamicBatcher(eng, max_queue=64, max_wait=0.0, deadline=10.0)
    try:
        # sacrificial blocker occupies the worker while the real
        # requests queue up behind it
        blocker = b.submit_async(np.zeros((1, 4), np.float32))
        time.sleep(0.05)
        reqs = [b.submit_async(np.zeros((3, 4), np.float32))
                for _ in range(4)]
        gate.set()
        b.wait(blocker)
        outs = [b.wait(r) for r in reqs]
        assert all(o.shape == (3, 1) for o in outs)
        # all four queued requests coalesced into ONE execution
        assert eng.batches == [1, 12]
        # row order preserved within the coalesced batch
        np.testing.assert_array_equal(outs[0][:, 0], [0, 1, 2])
        np.testing.assert_array_equal(outs[3][:, 0], [9, 10, 11])
        assert b.stats.get('batches[b32]') == 1   # 12 rows -> bucket 32
    finally:
        gate.set()
        b.close()


def test_batcher_overload_typed_rejection():
    gate = threading.Event()
    eng = FakeEngine(gate=gate)
    b = DynamicBatcher(eng, max_queue=2, max_wait=0.0, deadline=10.0)
    try:
        first = b.submit_async(np.zeros((33, 4), np.float32))  # worker busy
        time.sleep(0.05)                       # worker picked `first` up
        b.submit_async(np.zeros((1, 4), np.float32))
        b.submit_async(np.zeros((1, 4), np.float32))
        with pytest.raises(ServeOverloadError) as ei:
            b.submit_async(np.zeros((1, 4), np.float32))
        assert ei.value.max_queue == 2
        assert b.stats.get('rejected') == 1
        gate.set()
        assert b.wait(first).shape == (33, 1)
    finally:
        gate.set()
        b.close()


def test_batcher_deadline_typed_error_counted_once():
    gate = threading.Event()
    eng = FakeEngine(gate=gate)
    b = DynamicBatcher(eng, max_queue=8, max_wait=0.0, deadline=0.1)
    try:
        blocker = b.submit_async(np.zeros((1, 4), np.float32), deadline=10.0)
        time.sleep(0.05)                       # worker enters the gate
        doomed = b.submit_async(np.zeros((2, 4), np.float32), deadline=0.1)
        with pytest.raises(DeadlineExceededError) as ei:
            b.wait(doomed)
        assert ei.value.rows == 2
        gate.set()
        b.wait(blocker)
        # drain the abandoned request off the queue, then verify the shed
        # was counted ONCE (by the worker's drop path — the single owner
        # of terminal counts) and its forward never executed
        assert b.submit(np.zeros((3, 4), np.float32)).shape == (3, 1)
        assert b.stats.get('expired') == 1
        assert eng.batches == [1, 3]           # the doomed 2 rows: never run
    finally:
        gate.set()
        b.close()


def test_wrapper_fleet_routes_models_and_reports_budget(tmp_path):
    """serve_start(models=...) stands up the multi-model fleet: requests
    route per model id, the memory ledger prints with the stats."""
    a_dir, b_dir = tmp_path / 'a', tmp_path / 'b'
    a_dir.mkdir()
    b_dir.mkdir()
    rig_constant_class(make_net(1), cls=1).save_model(
        str(a_dir / '0001.model'))
    rig_constant_class(make_net(2), cls=3).save_model(
        str(b_dir / '0001.model'))
    net = make_net()
    net.serve_start(buckets='1,4', models={'a': str(a_dir),
                                           'b': str(b_dir)})
    try:
        x = np.zeros((2, 1, 1, 8), np.float32)
        assert (net.serve_predict(x, model='a') == 1).all()
        assert (net.serve_predict(x, model='b') == 3).all()
        stats = net.serve_stats()
        assert 'fleet-models_loaded:2' in stats
        assert 'fleet-bytes[a]' in stats
        net._fleet.evict('a')
        assert net._fleet.loaded() == ['b']
        # an evicted model reloads transparently on the next request
        assert (net.serve_predict(x, model='a') == 1).all()
    finally:
        net.serve_stop()


def test_batcher_drops_requests_expired_at_coalesce_close():
    """A request whose deadline passes WHILE the coalescing window is
    open is shed when the window closes — counted as a deadline miss,
    never forwarded to the engine (it must not waste a forward or a
    decode slot on an answer nobody will read)."""
    eng = FakeEngine()
    b = DynamicBatcher(eng, max_queue=8, max_wait=0.3, deadline=10.0)
    try:
        first = b.submit_async(np.zeros((1, 4), np.float32))
        # joins the window immediately (deadline still live at pop time),
        # then expires before the 0.3s window closes
        doomed = b.submit_async(np.zeros((2, 4), np.float32),
                                deadline=0.05)
        with pytest.raises(DeadlineExceededError):
            b.wait(doomed)
        assert b.wait(first).shape == (1, 1)
        assert eng.batches == [1], 'expired rows must not be forwarded'
        assert b.stats.get('expired') == 1
    finally:
        b.close()


def test_batcher_engine_error_propagates_per_request():
    b = DynamicBatcher(FakeEngine(fail=True), max_queue=8, max_wait=0.0,
                       deadline=5.0)
    try:
        with pytest.raises(RuntimeError, match='engine exploded'):
            b.submit(np.zeros((2, 4), np.float32))
        assert b.stats.get('engine_errors') == 1
    finally:
        b.close()


def test_batcher_survives_shape_mismatched_coalesce():
    """A shape-mismatched request must error per-request, not kill the
    worker thread (which would wedge the service while still admitting)."""
    gate = threading.Event()
    eng = FakeEngine(gate=gate)
    b = DynamicBatcher(eng, max_queue=16, max_wait=0.0, deadline=10.0)
    try:
        blocker = b.submit_async(np.zeros((1, 4), np.float32))
        time.sleep(0.05)
        good = b.submit_async(np.zeros((2, 4), np.float32))
        bad = b.submit_async(np.zeros((2, 9), np.float32))  # wrong width
        gate.set()
        b.wait(blocker)
        with pytest.raises(ValueError):
            b.wait(good)                 # coalesced batch fails together
        with pytest.raises(ValueError):
            b.wait(bad)
        # the worker survived: the service still serves
        assert b.submit(np.zeros((3, 4), np.float32)).shape == (3, 1)
    finally:
        gate.set()
        b.close()


def test_batcher_close_idempotent_and_rejects_after():
    b = DynamicBatcher(FakeEngine(), max_queue=8, max_wait=0.0, deadline=5.0)
    assert b.submit(np.zeros((1, 4), np.float32)).shape == (1, 1)
    assert b.close()
    assert b.close()                     # second close: no block, no raise
    with pytest.raises(ServeError):
        b.submit_async(np.zeros((1, 4), np.float32))


def test_batcher_drains_queue_on_close():
    gate = threading.Event()
    eng = FakeEngine(gate=gate)
    b = DynamicBatcher(eng, max_queue=16, max_wait=0.0, deadline=10.0)
    reqs = [b.submit_async(np.zeros((1, 4), np.float32)) for _ in range(5)]
    gate.set()
    assert b.close(timeout=10)
    for r in reqs:                       # graceful: nothing dropped
        assert b.wait(r).shape == (1, 1)


# --- registry / hot reload ------------------------------------------------

def save_model_with_digest(net, path):
    net.save_model(path)
    checkpoint.write_model_digest(path)


def test_registry_reload_state_machine(tmp_path):
    net = make_net()
    save_model_with_digest(net, str(tmp_path / '0000.model'))
    serve = wrapper.Net(dev='cpu', cfg=NET_CFG)
    serve.load_model(str(tmp_path / '0000.model'))
    eng = PredictEngine(serve._trainer, (1, 8))
    reg = ModelRegistry(eng, str(tmp_path), current=0)
    assert not reg.poll_once()           # nothing newer
    assert reg.states() == []

    v2 = rig_constant_class(make_net(seed=7))
    save_model_with_digest(v2, str(tmp_path / '0001.model'))
    assert reg.poll_once()
    assert reg.states() == ['DETECTED', 'VERIFYING', 'LOADING', 'WARMING',
                            'SWAPPED']
    assert reg.current == 1 and eng.version == 1
    d = np.random.RandomState(0).randn(4, 1, 1, 8).astype(np.float32)
    assert np.all(eng.predict(d) == 2.0)
    assert not reg.poll_once()           # idempotent: already current


def test_registry_rejects_corrupt_checkpoint_and_keeps_serving(tmp_path):
    net = make_net()
    save_model_with_digest(net, str(tmp_path / '0000.model'))
    eng = PredictEngine(net._trainer, (8,))
    d = np.random.RandomState(1).randn(3, 1, 1, 8).astype(np.float32)
    before = eng.predict_scores(d)
    reg = ModelRegistry(eng, str(tmp_path), current=0)

    v2 = make_net(seed=3)
    path = str(tmp_path / '0001.model')
    save_model_with_digest(v2, path)
    with open(path, 'r+b') as f:         # flip payload bytes post-digest
        f.seek(200)
        f.write(b'\xde\xad\xbe\xef')
    assert not reg.poll_once()
    assert reg.states()[-1] == 'REJECTED'
    assert reg.current == 0 and eng.swap_count == 0
    np.testing.assert_array_equal(eng.predict_scores(d), before)
    # persistent rejects blacklist after max_attempts polls (no hot loop)
    for _ in range(10):
        reg.poll_once()
    assert sum(1 for s in reg.states() if s == 'REJECTED') \
        == reg.retry.max_attempts


def test_verify_model_digest_malformed_sidecar_is_reason(tmp_path):
    """Malformed-but-valid-JSON sidecars must yield a rejection REASON,
    never an escaping TypeError — the registry blacklists on reasons."""
    net = make_net()
    path = str(tmp_path / '0000.model')
    net.save_model(path)
    assert checkpoint.verify_model_digest(path) is None   # no sidecar: ok
    side = checkpoint.model_digest_path(path)
    for payload in ('{"size": %d}' % os.path.getsize(path),  # missing crc
                    '[1, 2, 3]', '"nope"', 'not json at all'):
        with open(side, 'w') as f:
            f.write(payload)
        reason = checkpoint.verify_model_digest(path)
        assert isinstance(reason, str) and reason
    # and the registry turns it into a REJECTED cycle, old version serving
    eng = PredictEngine(net._trainer, (8,))
    reg = ModelRegistry(eng, str(tmp_path), current=-1)
    assert not reg.poll_once()
    assert reg.states()[-1] == 'REJECTED' and eng.swap_count == 0


def test_registry_falls_back_past_corrupt_newest(tmp_path):
    """A corrupt NEWEST checkpoint must not pin the server: the same
    poll falls back to the next-newest good candidate."""
    net = make_net()
    eng = PredictEngine(net._trainer, (8,))
    reg = ModelRegistry(eng, str(tmp_path), current=0)
    good = rig_constant_class(make_net(seed=13))
    save_model_with_digest(good, str(tmp_path / '0001.model'))
    bad_path = str(tmp_path / '0002.model')
    save_model_with_digest(make_net(seed=14), bad_path)
    with open(bad_path, 'r+b') as f:
        f.seek(150)
        f.write(b'\xba\xad')
    assert reg.poll_once()               # 0002 rejected, 0001 adopted
    assert reg.current == 1 and eng.version == 1
    states = reg.states()
    assert 'REJECTED' in states and states[-1] == 'SWAPPED'
    d = np.zeros((3, 1, 1, 8), np.float32)
    assert np.all(eng.predict(d) == 2.0)


def test_pred_buckets_bounds_streaming_paths(tmp_path):
    """forward_stream/predict_stream honor the ladder too: an iterator
    with varying batch sizes must not grow the compile cache."""
    from cxxnet_tpu.io.data import DataBatch
    net = make_net()
    tr = net._trainer
    tr.set_param('pred_buckets', '8')
    base = tr._forward_fn._cache_size()
    rng = np.random.RandomState(6)
    batches = [DataBatch(rng.randn(n, 1, 1, 8).astype(np.float32),
                         np.zeros((n, 1), np.float32))
               for n in (3, 5, 7)]
    chunks = list(tr.predict_stream(iter(batches)))
    assert [c.shape[0] for c in chunks] == [3, 5, 7]
    assert tr._forward_fn._cache_size() - base == 1
    # values identical to the unbucketed stream
    tr.set_param('pred_buckets', '0')
    for c, ref in zip(chunks, tr.predict_stream(iter(batches))):
        np.testing.assert_array_equal(c, ref)


def test_registry_rejects_structural_mismatch(tmp_path):
    other_cfg = NET_CFG.replace('layer[+1] = relu', 'layer[+1] = sigmoid')
    other = wrapper.Net(dev='cpu', cfg=other_cfg)
    other.init_model()
    path = str(tmp_path / 'other.model')
    other.save_model(path)
    net = make_net()
    eng = PredictEngine(net._trainer, (8,))
    with pytest.raises(ValueError, match='architecture'):
        load_model_params(eng, path)


def test_registry_watcher_thread_lifecycle(tmp_path):
    net = make_net()
    save_model_with_digest(net, str(tmp_path / '0000.model'))
    eng = PredictEngine(net._trainer, (8,))
    reg = ModelRegistry(eng, str(tmp_path), poll_interval=0.02, current=0)
    reg.start()
    reg.start()                          # idempotent
    v2 = rig_constant_class(make_net(seed=11))
    save_model_with_digest(v2, str(tmp_path / '0001.model'))
    deadline = time.monotonic() + 10
    while reg.current != 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert reg.current == 1
    assert reg.close(timeout=5)
    assert reg.close(timeout=5)          # idempotent


# --- acceptance: concurrent serve + hot reload, zero drops ----------------

def test_e2e_concurrent_serve_hot_reload_zero_drops(tmp_path):
    """N concurrent clients with mixed request sizes; mid-traffic the
    registry hot-swaps a new checkpoint.  Every request completes (zero
    drops), the engine compiled exactly len(buckets) programs, overload
    is a typed rejection, and post-swap requests serve the new params."""
    buckets = (1, 8, 32)
    net = make_net()
    save_model_with_digest(net, str(tmp_path / '0000.model'))
    serve = wrapper.Net(dev='cpu', cfg=NET_CFG)
    serve.load_model(str(tmp_path / '0000.model'))
    eng = PredictEngine(serve._trainer, buckets)
    eng.warm()
    bat = DynamicBatcher(eng, max_queue=256, max_wait=0.002, deadline=30.0)
    reg = ModelRegistry(eng, str(tmp_path), current=0)

    n_clients = 6
    completed = []
    errors = []
    submitted = [0] * n_clients
    stop = threading.Event()
    lock = threading.Lock()

    def client(cid):
        rng = np.random.RandomState(cid)
        while not stop.is_set():
            n = int(rng.randint(1, 13))
            submitted[cid] += 1
            try:
                scores = bat.submit(rng.randn(n, 1, 1, 8)
                                    .astype(np.float32))
                with lock:
                    completed.append((eng.version, n, scores.shape))
            except Exception as e:       # any error fails the test
                with lock:
                    errors.append((cid, e))

    def count(version=None):
        with lock:
            return len(completed) if version is None else \
                sum(1 for v, _, _ in completed if v == version)

    def wait_for(pred, what):
        deadline = time.monotonic() + 60
        while not pred():
            assert time.monotonic() < deadline, f'timed out: {what}'
            time.sleep(0.005)

    threads = [threading.Thread(target=client, args=(cid,))
               for cid in range(n_clients)]
    for t in threads:
        t.start()
    # traffic demonstrably flowing on v0, then swap mid-stream
    wait_for(lambda: count(0) >= 30, 'pre-swap traffic')
    v2 = rig_constant_class(make_net(seed=21))
    save_model_with_digest(v2, str(tmp_path / '0001.model'))
    assert reg.poll_once()
    # traffic demonstrably flowing on v1 before anyone stops
    wait_for(lambda: count(1) >= 30, 'post-swap traffic')
    stop.set()
    for t in threads:
        t.join(60)
    assert not errors, errors
    assert len(completed) == sum(submitted)              # zero drops
    assert all(shape == (n, 4) for _, n, shape in completed)
    # the compile cache stayed provably bounded through all of it
    assert eng.compile_count == len(buckets)
    # traffic continued across the swap: both versions actually served
    versions = {v for v, _, _ in completed}
    assert versions == {0, 1}
    # and requests after the swap serve the new params
    d = np.zeros((4, 1, 1, 8), np.float32)
    out = bat.submit(d)
    assert np.all(np.argmax(out, axis=1) == 2)
    bat.close()
    report = bat.report('serve')
    assert 'serve-requests:' in report and 'latency_ms' in report


# --- satellite: bounded predict compile cache (pred_buckets) --------------

def test_pred_buckets_bounds_wrapper_predict_compiles():
    net = make_net()
    tr = net._trainer
    tr.set_param('pred_buckets', '8')
    assert tr.pred_buckets == (8,)
    base = tr._forward_fn._cache_size()
    rng = np.random.RandomState(2)
    data = {n: rng.randn(n, 1, 1, 8).astype(np.float32)
            for n in (3, 5, 7, 8)}
    outs = {n: net.predict(d) for n, d in data.items()}
    assert all(outs[n].shape == (n,) for n in data)
    # four novel request sizes, ONE compiled program (the 8-bucket)
    assert tr._forward_fn._cache_size() - base == 1
    # and values match the unbucketed path exactly
    tr.set_param('pred_buckets', '0')    # '0' disables
    assert tr.pred_buckets is None
    for n, d in data.items():
        np.testing.assert_array_equal(outs[n], net.predict(d))


def test_pred_buckets_mesh_divisibility_clear_error():
    """Same invariant the engine enforces at startup: on a multi-device
    mesh a bucket that doesn't divide the data axis fails with the clear
    config error, not an opaque sharding error mid-predict."""
    net = wrapper.Net(dev='cpu:0-7', cfg=NET_CFG)
    net.init_model()
    net._trainer.set_param('pred_buckets', '1,8')
    with pytest.raises(ValueError, match='data axis'):
        net.predict(np.zeros((3, 1, 1, 8), np.float32))
    net._trainer.set_param('pred_buckets', '8,32')
    assert net.predict(np.zeros((3, 1, 1, 8), np.float32)).shape == (3,)


def test_pred_buckets_extract_and_capi_batch():
    net = make_net()
    net._trainer.set_param('pred_buckets', '1,8')
    rng = np.random.RandomState(4)
    d = rng.randn(5, 1, 1, 8).astype(np.float32)
    feat = net.extract(d, 'top[-3]')     # relu output (width 16)
    assert feat.shape[0] == 5
    out = capi.net_predict_batch(net, memoryview(d.tobytes()), (5, 1, 1, 8))
    np.testing.assert_array_equal(out, net.predict(d))


# --- satellite: streaming iter paths at the C ABI -------------------------

def make_mnist_iter_cfg(tmp_path, batch_size=10):
    pi, pl, img, y = write_mnist(str(tmp_path))
    return f"""
iter = mnist
  path_img = "{pi}"
  path_label = "{pl}"
  batch_size = {batch_size}
  silent = 1
iter = end
"""


def test_net_predict_iter_streams_whole_dataset(tmp_path):
    cfg = make_mnist_iter_cfg(tmp_path)
    net = wrapper.Net(dev='cpu', cfg=NET_CFG.replace(
        'input_shape = 1,1,8', 'input_shape = 1,1,64'))
    net.init_model()
    it = wrapper.DataIter(cfg)
    out = capi.net_predict_iter(net, it)
    assert out.shape == (50,)            # whole dataset, pads trimmed
    # matches batch-by-batch prediction
    it.before_first()
    chunks = []
    while it.next():
        chunks.append(net.predict(it))
    np.testing.assert_array_equal(out, np.concatenate(chunks))
    # repeatable: the ABI call rewinds the iterator itself
    np.testing.assert_array_equal(out, capi.net_predict_iter(net, it))


def test_net_extract_iter_streams_whole_dataset(tmp_path):
    cfg = make_mnist_iter_cfg(tmp_path)
    net = wrapper.Net(dev='cpu', cfg=NET_CFG.replace(
        'input_shape = 1,1,8', 'input_shape = 1,1,64'))
    net.init_model()
    it = wrapper.DataIter(cfg)
    out = capi.net_extract_iter(net, it, 'top[-3]')
    assert out.shape == (50, 1, 1, 16)   # relu width, whole dataset
    it.before_first()
    it.next()
    np.testing.assert_allclose(out[:10].reshape(10, 16),
                               net.extract(it, 'top[-3]').reshape(10, 16),
                               rtol=0, atol=1e-6)


def test_predict_stream_is_o_batch(tmp_path):
    """The wrapper-level generator yields one trimmed chunk per batch —
    the consumer controls peak memory, not the ABI."""
    cfg = make_mnist_iter_cfg(tmp_path, batch_size=10)
    net = wrapper.Net(dev='cpu', cfg=NET_CFG.replace(
        'input_shape = 1,1,8', 'input_shape = 1,1,64'))
    net.init_model()
    it = wrapper.DataIter(cfg)
    sizes = [chunk.shape[0] for chunk in net.predict_stream(it)]
    assert sizes == [10] * 5


# --- satellite: tail-batch predict semantics ------------------------------

def test_predict_stream_trims_exact_tail_pad(tmp_path):
    """round_batch=0: the last short batch is padded to full size with
    ``num_batch_padd`` synthetic rows — predict_stream must drop exactly
    those, so the stream yields exactly the dataset's row count."""
    lst = make_img_dataset(str(tmp_path), n=10)
    cfg = [('iter', 'img'), ('image_list', lst),
           ('image_root', str(tmp_path)), ('input_shape', '3,16,16'),
           ('batch_size', '4'), ('round_batch', '0'), ('silent', '1'),
           ('iter', 'end')]
    from cxxnet_tpu.io.data import create_iterator
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    assert [b.num_batch_padd for b in batches] == [0, 0, 2]
    assert batches[-1].pad_synthetic

    conv_cfg = """
netconfig=start
layer[+1] = flatten
layer[+1] = fullc:fc
  nhidden = 3
layer[+0] = softmax
netconfig=end
input_shape = 3,16,16
batch_size = 4
dev = cpu
eta = 0.1
"""
    net = wrapper.Net(dev='cpu', cfg=conv_cfg)
    net.init_model()
    chunks = list(net._trainer.predict_stream(iter(it)))
    assert [c.shape[0] for c in chunks] == [4, 4, 2]
    # the tail chunk is the first 2 rows of the padded forward — the
    # synthetic (repeated-last-instance) rows never surface
    full = net._trainer._forward_nodes(batches[-1], [
        net._trainer.net.cfg.layers[-1].nindex_out[-1]])[0]
    np.testing.assert_array_equal(
        chunks[-1], wrapper.NetTrainer._pred_transform(full[:2]))


# --- satellite: re-entrant pipeline shutdown ------------------------------

def test_thread_buffer_iterator_close_idempotent(tmp_path):
    from cxxnet_tpu.io.data import ThreadBufferIterator, create_iterator
    lst = make_img_dataset(str(tmp_path), n=8)
    base = create_iterator(
        [('iter', 'img'), ('image_list', lst),
         ('image_root', str(tmp_path)), ('input_shape', '3,16,16'),
         ('batch_size', '4'), ('silent', '1'), ('iter', 'end')])
    it = ThreadBufferIterator(base)
    it.init()
    assert len(list(it)) == 2
    assert it.close(timeout=5)
    t0 = time.monotonic()
    assert it.close(timeout=5)           # second close: no block, no raise
    assert time.monotonic() - t0 < 1.0
    # the buffer stays usable after close (serve-loop re-entry)
    assert len(list(it)) == 2
    assert it.close(timeout=5)


def test_thread_buffer_close_concurrent():
    from cxxnet_tpu.utils.thread_buffer import ThreadBuffer
    buf = ThreadBuffer(lambda: iter(range(100)), buffer_size=2)
    got = []
    for x in buf:
        got.append(x)
        if len(got) == 3:
            break
    results = []
    ths = [threading.Thread(target=lambda: results.append(
        buf.close(timeout=5))) for _ in range(4)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    assert results == [True] * 4         # every concurrent close returns


# --- CLI: task=serve end to end -------------------------------------------

def _run_cli(conf_path, cwd, *overrides, timeout=300):
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run(
        [sys.executable, '-m', 'cxxnet_tpu.main', conf_path, *overrides],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, (r.stdout, r.stderr)
    return r


def test_cli_task_serve_matches_task_pred(tmp_path):
    pi, pl, img, y = write_mnist(str(tmp_path))
    conf = f"""
data = train
iter = mnist
  path_img = "{pi}"
  path_label = "{pl}"
  batch_size = 10
  silent = 1
iter = end
pred = {tmp_path}/pred_serve.txt
iter = mnist
  path_img = "{pi}"
  path_label = "{pl}"
  batch_size = 10
  silent = 1
iter = end
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 16
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 3
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 10
dev = cpu
eta = 0.3
num_round = 1
model_dir = {tmp_path}/models
metric = error
"""
    cp = tmp_path / 'serve.conf'
    cp.write_text(conf)
    _run_cli(str(cp), str(tmp_path), 'silent=1')
    model = f'{tmp_path}/models/0001.model'
    # train wrote the hot-reload digest sidecar alongside the model
    assert os.path.exists(model + '.crc32')
    assert checkpoint.verify_model_digest(model) is None
    r = _run_cli(str(cp), str(tmp_path), 'task=serve',
                 f'model_in={model}', 'serve.buckets=1,8,16',
                 'serve.deadline=60', 'silent=1')
    assert 'compiled 3 programs for 3 buckets' in r.stdout
    assert '[serve]' in r.stderr and 'serve-requests:' in r.stderr
    r2 = _run_cli(str(cp), str(tmp_path), 'task=pred',
                  f'model_in={model}', f'pred={tmp_path}/pred_ref.txt',
                  'silent=1')
    assert (tmp_path / 'pred_serve.txt').read_text() \
        == (tmp_path / 'pred_ref.txt').read_text()
