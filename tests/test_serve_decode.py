"""Continuous-batching decode engine suite (serve/decode.py).

The load-bearing claim is the bitwise-twin discipline: a request's token
stream through the slot/page engine equals an offline
``transformer.generate`` call with the same seed — no matter when the
request joined the running loop, which slots shared its steps, or how
its cache was paged.  Plus the paged-vs-dense logit identity, token-
granular shed/deadline errors, the multi-model memory budgeter, and the
``%04d.lm`` registry watch.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax

from cxxnet_tpu.models import transformer as T
from cxxnet_tpu.runtime.faults import (DeadlineExceededError,
                                       DecodePagesExhaustedError,
                                       DecodeSlotsExhaustedError,
                                       MemoryBudgetExceededError,
                                       TokenDeadlineExceededError)
from cxxnet_tpu.serve.decode import (DecodeEngine, DecodeService,
                                     LM_PATTERN, lm_loader, load_lm_params,
                                     save_lm_params)
from cxxnet_tpu.serve.registry import (MemoryBudgeter, ModelRegistry,
                                       MultiModelRegistry)

pytestmark = pytest.mark.serve_decode

CFG = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                          d_ff=48, num_stages=2, seq_len=32, attn='local')


def _params(seed: int = 0):
    return T.init_params(np.random.RandomState(seed), CFG)


def _prompt(rng, lo=1, hi=12):
    return rng.randint(0, CFG.vocab_size,
                       (1, int(rng.randint(lo, hi)))).astype(np.int32)


def _wait_ok(req, timeout=60):
    assert req.event.wait(timeout), 'request never completed'
    if req.error is not None:
        raise req.error
    return req.result


def _offline(params, prompt, max_new, temperature=0.0, rng=None,
             eos_id=None):
    return np.asarray(T.generate(params, prompt, max_new, CFG,
                                 temperature=temperature, rng=rng,
                                 eos_id=eos_id))[0]


def _assert_twin(got, off):
    """Engine streams stop at the first EOS; offline keeps emitting it."""
    got = np.asarray(got)
    assert len(got) >= 1
    np.testing.assert_array_equal(got, off[:len(got)])
    if len(got) < len(off):
        assert (off[len(got):] == off[len(got) - 1]).all()


@pytest.fixture(scope='module')
def engine():
    eng = DecodeEngine(_params(), CFG, slots=4, pages=64, page_size=8,
                       max_prompt=16, max_new_bound=64)
    yield eng
    eng.close(30)


# --- paged-vs-dense bitwise identity ---------------------------------------

class TestPagedVsDense:
    def _setup_caches(self, w_pad: int):
        """Dense cache via prefill + paged pool holding the same rows."""
        params = _params()
        rng = np.random.RandomState(3)
        s0 = 8
        prompt = rng.randint(0, 64, (2, s0)).astype(np.int32)
        ks, vs, logits0 = jax.jit(
            lambda p, t, w: T.prefill_kv(p, t, w, CFG))(
                params, prompt, np.int32(w_pad))
        hd = CFG.d_model // CFG.num_heads
        Tlen = 32
        kc = np.zeros((CFG.num_stages, 2, Tlen, CFG.num_heads, hd),
                      np.float32)
        vc = np.zeros_like(kc)
        kc[:, :, :s0] = np.asarray(ks)
        vc[:, :, :s0] = np.asarray(vs)
        tok0 = np.asarray(logits0.argmax(-1), np.int32)
        return params, kc, vc, tok0, s0, Tlen

    @pytest.mark.parametrize('w_pad', [0, 3])
    def test_paged_step_logits_bitwise_equal_dense(self, w_pad):
        """One decode step over a page-table-gathered cache must produce
        BITWISE the dense-cache logits — including the left-pad
        bucket-masking leg (w>0: pad slots never attended)."""
        params, kc, vc, tok0, s0, Tlen = self._setup_caches(w_pad)
        hd = CFG.d_model // CFG.num_heads
        ps, n_slots = 8, 2
        pp = Tlen // ps                                   # logical pages
        # scatter the dense rows into a shuffled physical page pool
        n_phys = n_slots * pp + 3
        kpool = np.zeros((CFG.num_stages, n_phys, ps, CFG.num_heads, hd),
                         np.float32)
        vpool = np.zeros_like(kpool)
        rng = np.random.RandomState(9)
        phys = rng.permutation(np.arange(1, n_phys))[:n_slots * pp]
        table = phys.reshape(n_slots, pp).astype(np.int32)
        for b in range(n_slots):
            for lp in range(pp):
                kpool[:, table[b, lp]] = kc[:, b, lp * ps:(lp + 1) * ps]
                vpool[:, table[b, lp]] = vc[:, b, lp * ps:(lp + 1) * ps]

        # dense reference: the scalar-t path generate() itself scans
        t_scalar = np.int32(s0)
        w_scalar = np.int32(w_pad)
        dense = jax.jit(lambda p, tok, kc, vc, t, w: T.decode_step(
            p, CFG, tok, kc, vc, t, w))(
                params, tok0, jax.numpy.asarray(kc),
                jax.numpy.asarray(vc), t_scalar, w_scalar)

        # paged path: gather pages -> per-row t/w vectors (the engine's
        # step shape), same shared decode_step math
        def paged(p, kpool, vpool, table, tok, t, w):
            kcg = kpool[:, table].reshape(CFG.num_stages, n_slots, Tlen,
                                          CFG.num_heads, hd)
            vcg = vpool[:, table].reshape(CFG.num_stages, n_slots, Tlen,
                                          CFG.num_heads, hd)
            return T.decode_step(p, CFG, tok, kcg, vcg, t, w)

        tv = np.full((n_slots,), s0, np.int32)
        wv = np.full((n_slots,), w_pad, np.int32)
        pg = jax.jit(paged)(params, kpool, vpool, table, tok0, tv, wv)

        np.testing.assert_array_equal(np.asarray(dense[0]),
                                      np.asarray(pg[0]))
        # the newly written K/V rows agree too (what the engine scatters)
        np.testing.assert_array_equal(np.asarray(dense[3]),
                                      np.asarray(pg[3]))
        np.testing.assert_array_equal(np.asarray(dense[4]),
                                      np.asarray(pg[4]))


# --- flash paged decode (ops.pallas_kernels.paged_flash_decode) -------------

class TestFlashPagedDecode:
    """serve.flash_decode: the Pallas kernel that reads KV pages in
    place must be BITWISE-equal to the gather-then-dense path — at step
    level and over whole streams — on the CPU interpret=True path."""

    @pytest.mark.parametrize('w_pad', [0, 3])
    def test_flash_step_bitwise_equal_dense(self, w_pad):
        """decode_step_paged (scatter + in-place kernel) vs gather +
        decode_step + scatter-back: logits AND both pools bitwise,
        including the left-pad leg and per-slot mixed positions."""
        params = _params()
        rng = np.random.RandomState(11)
        S, ps, pp = 3, 8, 4
        Tlen = ps * pp
        n_phys = S * pp + 2
        hd = CFG.d_model // CFG.num_heads
        kpool = rng.randn(CFG.num_stages, n_phys, ps, CFG.num_heads,
                          hd).astype(np.float32)
        vpool = rng.randn(CFG.num_stages, n_phys, ps, CFG.num_heads,
                          hd).astype(np.float32)
        phys = rng.permutation(np.arange(1, n_phys))[:S * pp]
        table = phys.reshape(S, pp).astype(np.int32)
        pos = np.asarray([5, 13, 20], np.int32)   # mid-stream, per-slot
        w = np.full((S,), w_pad, np.int32)
        tok = rng.randint(0, 64, (S,)).astype(np.int32)

        def dense(p, kpool, vpool, table, tok, t, wv):
            kc = kpool[:, table].reshape(CFG.num_stages, S, Tlen,
                                         CFG.num_heads, hd)
            vc = vpool[:, table].reshape(CFG.num_stages, S, Tlen,
                                         CFG.num_heads, hd)
            logits, _, _, knew, vnew = T.decode_step(p, CFG, tok, kc, vc,
                                                     t, wv)
            page = table[jax.numpy.arange(S), t // ps]
            off = t % ps
            si = jax.numpy.arange(CFG.num_stages)[:, None]
            kpool = kpool.at[si, page[None, :], off[None, :]].set(knew)
            vpool = vpool.at[si, page[None, :], off[None, :]].set(vnew)
            return logits, kpool, vpool

        dl, dk, dv = jax.jit(dense)(params, kpool, vpool, table, tok,
                                    pos, w)
        fl, fk, fv = jax.jit(
            lambda p, kp, vp, tb, tk, t, wv: T.decode_step_paged(
                p, CFG, tk, kp, vp, tb, t, wv))(
            params, kpool, vpool, table, tok, pos, w)
        np.testing.assert_array_equal(np.asarray(dl), np.asarray(fl))
        np.testing.assert_array_equal(np.asarray(dk), np.asarray(fk))
        np.testing.assert_array_equal(np.asarray(dv), np.asarray(fv))

    def _twin_engines(self, **kw):
        params = _params()
        dense = DecodeEngine(params, CFG, slots=4, pages=64, page_size=8,
                             max_prompt=16, max_new_bound=64,
                             flash_decode=0, **kw)
        flash = DecodeEngine(params, CFG, slots=4, pages=64, page_size=8,
                             max_prompt=16, max_new_bound=64,
                             flash_decode=1, **kw)
        assert not dense.use_flash and flash.use_flash
        return dense, flash

    def test_flash_streams_bitwise_equal_gather(self):
        """Greedy + sampled mixed-length staggered traffic: the flash
        engine's streams equal the gather engine's AND the offline
        generate twins, token for token."""
        dense, flash = self._twin_engines()
        try:
            rng = np.random.RandomState(21)
            prompts = [_prompt(rng) for _ in range(6)]
            keys = [None, None, None] + [jax.random.PRNGKey(70 + i)
                                         for i in range(3)]
            temps = [0.0, 0.0, 0.0, 0.9, 0.9, 1.3]
            outs = {}
            for eng in (dense, flash):
                reqs = []
                for p, k, tp in zip(prompts, keys, temps):
                    reqs.append(eng.submit_direct(p, max_new=7,
                                                  temperature=tp, rng=k))
                    time.sleep(0.005)   # staggered: later joins mid-run
                outs[eng] = [_wait_ok(r) for r in reqs]
            for i, (p, k, tp) in enumerate(zip(prompts, keys, temps)):
                np.testing.assert_array_equal(outs[dense][i],
                                              outs[flash][i])
                _assert_twin(outs[flash][i],
                             _offline(flash.params, p, 7,
                                      temperature=tp, rng=k))
        finally:
            dense.close(30)
            flash.close(30)

    def test_flash_mid_stream_join(self):
        """A request admitted while another stream is mid-decode joins at
        a token boundary and still twins — on both legs, bitwise."""
        dense, flash = self._twin_engines()
        try:
            rng = np.random.RandomState(22)
            p1, p2 = _prompt(rng), _prompt(rng)
            for eng in (dense, flash):
                r1 = eng.submit_direct(p1, max_new=24)
                while len(r1.tokens) < 4:     # provably mid-stream
                    time.sleep(0.002)
                r2 = eng.submit_direct(p2, max_new=6)
                _assert_twin(_wait_ok(r1), _offline(eng.params, p1, 24))
                _assert_twin(_wait_ok(r2), _offline(eng.params, p2, 6))
        finally:
            dense.close(30)
            flash.close(30)

    def test_flash_eos_reclaims_pages(self):
        """EOS mid-stream on the flash leg: prefix twin holds and every
        page returns to the pool."""
        params = _params()
        rng = np.random.RandomState(23)
        p = _prompt(rng)
        base = _offline(params, p, 12)
        eos = int(base[2])
        eng = DecodeEngine(params, CFG, slots=2, pages=32, page_size=8,
                           max_prompt=16, max_new_bound=16, eos_id=eos,
                           flash_decode=1)
        try:
            free0 = len(eng._free_pages)
            got = _wait_ok(eng.submit_direct(p, max_new=12))
            _assert_twin(got, _offline(params, p, 12, eos_id=eos))
            assert got[-1] == eos and len(got) <= 12
            deadline = time.time() + 5
            while len(eng._free_pages) != free0 and time.time() < deadline:
                time.sleep(0.01)
            assert len(eng._free_pages) == free0
        finally:
            eng.close(30)

    def test_flash_gate_tristate(self, monkeypatch):
        """serve.flash_decode=1/0 forces; auto defers to pallas_mode():
        off on CPU auto, on when CXXNET_PALLAS=1."""
        from cxxnet_tpu.ops import pallas_kernels as PK
        if PK.pltpu is None:
            pytest.skip('pallas TPU memory spaces unavailable')
        monkeypatch.delenv('CXXNET_PALLAS', raising=False)
        assert PK.decode_use_flash(1) and PK.decode_use_flash('true')
        assert not PK.decode_use_flash(0)
        assert not PK.decode_use_flash('auto')      # CPU: interpret-only
        assert not PK.decode_use_flash(None)
        monkeypatch.setenv('CXXNET_PALLAS', '1')
        assert PK.decode_use_flash(None) and PK.decode_use_flash('auto')
        assert not PK.decode_use_flash(0)           # explicit key wins
        monkeypatch.setenv('CXXNET_PALLAS', '0')
        assert not PK.decode_use_flash(None)
        assert PK.decode_use_flash(1)               # explicit key wins

    def test_resident_bytes_includes_kv_pool(self):
        """The budgeter ledger entry is params + the FULL paged pool:
        pages x page_size x stages x heads x head_dim x dtype, K and V —
        pinned closed-form so the dominant allocation can never silently
        fall out of eviction decisions again."""
        params = _params()
        eng = DecodeEngine(params, CFG, slots=2, pages=48, page_size=8,
                           max_prompt=16, max_new_bound=16)
        try:
            hd = CFG.d_model // CFG.num_heads
            itemsize = jax.numpy.dtype(CFG.dtype).itemsize
            pool = 2 * CFG.num_stages * 48 * 8 * CFG.num_heads * hd \
                * itemsize
            pbytes = sum(np.asarray(l).nbytes
                         for l in jax.tree.leaves(params))
            assert eng.resident_bytes() == pool + pbytes
            assert pool > pbytes   # the pool IS the dominant allocation
        finally:
            eng.close(30)


# --- stream twins -----------------------------------------------------------

class TestStreamTwins:
    def test_greedy_staggered_mixed_lengths(self, engine):
        """Mixed prompt lengths, staggered joins: every stream equals
        its offline generate twin; emissions are incremental."""
        rng = np.random.RandomState(1)
        prompts = [_prompt(rng) for _ in range(6)]
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(engine.submit_direct(p, max_new=5 + i))
            time.sleep(0.01)            # later requests join mid-decode
        for i, (p, r) in enumerate(zip(prompts, reqs)):
            got = _wait_ok(r)
            assert len(got) == 5 + i
            _assert_twin(got, _offline(engine.params, p, 5 + i))
            assert len(r.token_times) == len(got)
            assert all(b >= a for a, b in
                       zip(r.token_times, r.token_times[1:]))

    def test_sampled_rng_schedule_matches_offline(self, engine):
        """Per-request sampling keys: stream n pulls key n of
        split(rng, max_new+1) — exactly generate()'s schedule, even with
        slots sharing steps."""
        rng = np.random.RandomState(2)
        prompts = [_prompt(rng) for _ in range(4)]
        keys = [jax.random.PRNGKey(50 + i) for i in range(4)]
        reqs = [engine.submit_direct(p, max_new=8, temperature=0.8,
                                     rng=k)
                for p, k in zip(prompts, keys)]
        for p, k, r in zip(prompts, keys, reqs):
            got = _wait_ok(r)
            _assert_twin(got, _offline(engine.params, p, 8,
                                       temperature=0.8, rng=k))

    def test_mixed_greedy_and_sampled_share_steps(self, engine):
        rng = np.random.RandomState(7)
        pg, ps_ = _prompt(rng), _prompt(rng)
        key = jax.random.PRNGKey(123)
        r1 = engine.submit_direct(pg, max_new=6)
        r2 = engine.submit_direct(ps_, max_new=6, temperature=1.2,
                                  rng=key)
        _assert_twin(_wait_ok(r1), _offline(engine.params, pg, 6))
        _assert_twin(_wait_ok(r2), _offline(engine.params, ps_, 6,
                                            temperature=1.2, rng=key))

    def test_max_new_one_is_prefill_only(self, engine):
        rng = np.random.RandomState(8)
        p = _prompt(rng)
        got = _wait_ok(engine.submit_direct(p, max_new=1))
        assert got.shape == (1,)
        _assert_twin(got, _offline(engine.params, p, 1))


# --- slot/page lifecycle ----------------------------------------------------

class TestSlotLifecycle:
    def test_eos_frees_slot_early_and_stream_prefix_matches(self):
        params = _params()
        rng = np.random.RandomState(4)
        p = _prompt(rng)
        base = _offline(params, p, 12)
        eos = int(base[2])              # fires at stream position 2
        eng = DecodeEngine(params, CFG, slots=2, pages=32, page_size=8,
                           max_prompt=16, max_new_bound=16, eos_id=eos)
        try:
            free0 = len(eng._free_pages)
            got = _wait_ok(eng.submit_direct(p, max_new=12))
            off = _offline(params, p, 12, eos_id=eos)
            _assert_twin(got, off)
            assert got[-1] == eos and len(got) <= 12
            deadline = time.time() + 5
            while len(eng._free_pages) != free0 and time.time() < deadline:
                time.sleep(0.01)
            assert len(eng._free_pages) == free0, \
                'EOS must return every page to the pool'
        finally:
            eng.close(30)

    def test_queueing_when_slots_full(self):
        """More requests than slots: later ones wait, join as slots
        free, and still match their offline twins."""
        params = _params()
        svc = DecodeService(params, CFG, slots=1, pages=32, page_size=8,
                            max_prompt=16, max_new_bound=16,
                            deadline=60.0)
        try:
            rng = np.random.RandomState(5)
            prompts = [_prompt(rng) for _ in range(3)]
            reqs = [svc.submit_async(p, 6) for p in prompts]
            for p, r in zip(prompts, reqs):
                got = svc.batcher.wait(r)
                _assert_twin(got, _offline(params, p, 6))
        finally:
            svc.close(30)

    def test_token_deadline_mid_stream(self, engine):
        """A deadline that expires mid-stream sheds at token granularity:
        typed error carrying the emitted count, slot and pages freed."""
        rng = np.random.RandomState(6)
        p = _prompt(rng)
        req = engine.submit_direct(p, max_new=64, deadline=0.0001)
        assert req.event.wait(30)
        assert isinstance(req.error, TokenDeadlineExceededError)
        assert req.error.tokens_emitted >= 1
        assert len(req.tokens) == req.error.tokens_emitted
        deadline = time.time() + 5
        while engine.busy() and time.time() < deadline:
            time.sleep(0.01)
        assert not engine.busy()

    def test_page_pool_exhaustion_preempts_youngest(self):
        params = _params()
        eng = DecodeEngine(params, CFG, slots=2, pages=12, page_size=2,
                           max_prompt=8, max_new_bound=8)
        try:
            rng = np.random.RandomState(7)
            p1, p2 = _prompt(rng, 1, 4), _prompt(rng, 1, 4)
            r1 = eng.submit_direct(p1, max_new=8)
            r2 = eng.submit_direct(p2, max_new=8)
            assert r1.event.wait(60) and r2.event.wait(60)
            # oldest stream finishes; the youngest is the typed victim
            assert r1.error is None
            _assert_twin(r1.result, _offline(params, p1, 8))
            assert isinstance(r2.error, DecodePagesExhaustedError)
            assert r2.error.tokens_emitted >= 1
        finally:
            eng.close(30)

    def test_unshared_pages_refcount_to_zero_and_low_water_tracked(self):
        """The refcount plumbing (PR 12 prefix sharing) is invisible on
        the unshared path: every page a retired stream held goes back to
        refcount 0 / the free list, and the free-page low-water mark
        gauge records the deepest draw."""
        params = _params()
        eng = DecodeEngine(params, CFG, slots=2, pages=32, page_size=8,
                           max_prompt=16, max_new_bound=16)
        try:
            rng = np.random.RandomState(13)
            for _ in range(2):
                p = _prompt(rng)
                _assert_twin(_wait_ok(eng.submit_direct(p, max_new=6)),
                             _offline(params, p, 6))
            deadline = time.time() + 5
            while time.time() < deadline:
                with eng._cond:
                    if len(eng._free_pages) == eng.n_pages - 1:
                        break
                time.sleep(0.01)
            with eng._cond:
                assert len(eng._free_pages) == eng.n_pages - 1
                assert (eng._page_refs == 0).all()
                assert eng._free_min < eng.n_pages - 1
            assert 'pg-free_pages_min' in eng.report('pg')
        finally:
            eng.close(30)

    def test_inadmissible_requests_typed(self, engine):
        rng = np.random.RandomState(9)
        r = engine.submit_direct(rng.randint(0, 64, (1, 200)), max_new=4)
        assert isinstance(r.error, DecodeSlotsExhaustedError)
        r = engine.submit_direct(_prompt(rng), max_new=1000)
        assert isinstance(r.error, DecodeSlotsExhaustedError)


# --- hot swap ---------------------------------------------------------------

class TestHotSwap:
    def test_swap_mid_decode_drains_in_flight_on_old_params(self):
        pa, pb = _params(0), _params(11)
        eng = DecodeEngine(pa, CFG, slots=2, pages=64, page_size=8,
                           max_prompt=16, max_new_bound=64)
        try:
            rng = np.random.RandomState(10)
            p1, p2 = _prompt(rng), _prompt(rng)
            r1 = eng.submit_direct(p1, max_new=48)
            time.sleep(0.02)            # r1 is mid-decode
            assert not r1.event.is_set()
            eng.swap_params(pb, version='B')   # blocks through the drain
            assert r1.event.is_set(), 'swap returned before drain'
            assert r1.error is None, 'zero dropped requests across swap'
            _assert_twin(r1.result, _offline(pa, p1, 48))
            assert eng.version == 'B' and eng.swap_count == 1
            r2 = eng.submit_direct(p2, max_new=8)
            _assert_twin(_wait_ok(r2), _offline(pb, p2, 8))
        finally:
            eng.close(30)

    def test_registry_hot_swap_mid_decode_zero_drops(self, tmp_path):
        """The acceptance leg: the registry cycle lands a newer ``.lm``
        while a stream is mid-decode — the swap drains (in-flight
        finishes on the OLD params), nothing drops, and the next request
        serves the new checkpoint."""
        pa, pb = _params(0), _params(21)
        mdir = tmp_path / 'lms'
        mdir.mkdir()
        save_lm_params(str(mdir / '0001.lm'), pa)
        eng = DecodeEngine(pa, CFG, slots=2, pages=64, page_size=8,
                           max_prompt=16, max_new_bound=64)
        reg = ModelRegistry(eng, str(mdir), current=1,
                            pattern=LM_PATTERN, loader=lm_loader)
        try:
            assert not reg.poll_once()         # nothing newer
            rng = np.random.RandomState(12)
            p1, p2 = _prompt(rng), _prompt(rng)
            r1 = eng.submit_direct(p1, max_new=48)   # long, mid-decode
            assert not r1.event.is_set()
            save_lm_params(str(mdir / '0002.lm'), pb)
            assert reg.poll_once()             # verify→load→warm→SWAP
            assert reg.current == 2
            assert 'SWAPPED' in reg.states()
            assert r1.event.is_set(), 'poll returned before the drain'
            assert r1.error is None, 'zero dropped requests across swap'
            _assert_twin(r1.result, _offline(pa, p1, 48))
            _assert_twin(_wait_ok(eng.submit_direct(p2, max_new=6)),
                         _offline(pb, p2, 6))
        finally:
            eng.close(30)

    def test_registry_rejects_corrupt_lm_and_keeps_serving(self, tmp_path):
        pa, pb = _params(0), _params(22)
        mdir = tmp_path / 'lms'
        mdir.mkdir()
        save_lm_params(str(mdir / '0001.lm'), pa)
        eng = DecodeEngine(pa, CFG, slots=2, pages=32, page_size=8,
                           max_prompt=16, max_new_bound=16)
        reg = ModelRegistry(eng, str(mdir), current=1,
                            pattern=LM_PATTERN, loader=lm_loader)
        try:
            path = str(mdir / '0002.lm')
            save_lm_params(path, pb)
            with open(path, 'r+b') as f:        # silent byte corruption
                f.seek(100)
                f.write(b'\xff\xff\xff\xff')
            assert not reg.poll_once()
            assert 'REJECTED' in reg.states()
            assert reg.current == 1
            rng = np.random.RandomState(13)
            p = _prompt(rng)
            _assert_twin(_wait_ok(eng.submit_direct(p, max_new=4)),
                         _offline(pa, p, 4))    # old params keep serving
        finally:
            eng.close(30)


# --- memory budgeter --------------------------------------------------------

class _StubEngine:
    def __init__(self, nbytes, busy=False):
        self.nbytes = nbytes
        self._busy = busy
        self.closed = False
        self.version = 0

    def resident_bytes(self):
        return self.nbytes

    def busy(self):
        return self._busy

    def close(self, timeout=None):
        self.closed = True


class TestBudgeter:
    def test_ledger_accounting(self):
        b = MemoryBudgeter(100)
        b.account('a', 60)
        b.account('b', 30)
        assert b.usage() == 90 and b.over_budget() == 0
        b.account('c', 30)
        assert b.over_budget() == 20
        assert b.release('a') == 60
        assert b.usage() == 60
        assert MemoryBudgeter(0).over_budget() == 0   # unbounded

    def test_evicts_coldest_never_serving(self):
        fleet = MultiModelRegistry(mem_budget=130)
        engines = {}

        def mk(mid, nbytes, busy=False):
            def factory():
                engines[mid] = _StubEngine(nbytes, busy)
                return engines[mid]
            return factory

        fleet.add_model('a', mk('a', 60), load=True)
        time.sleep(0.01)
        fleet.add_model('b', mk('b', 60), load=True)
        assert fleet.loaded() == ['a', 'b']
        # loading c (60) pushes past 130: 'a' is coldest -> evicted
        fleet.add_model('c', mk('c', 60), load=True)
        assert fleet.loaded() == ['b', 'c']
        assert engines['a'].closed
        assert fleet.evictions == 1
        # touch b (hot), then reload a: c is now coldest
        fleet.get('b')
        fleet.get('a')
        assert fleet.loaded() == ['a', 'b']

    def test_budget_exceeded_when_everything_is_serving(self):
        fleet = MultiModelRegistry(mem_budget=100)
        fleet.add_model('serving', lambda: _StubEngine(80, busy=True),
                        load=True)
        fleet.add_model('cold', lambda: _StubEngine(80))
        with pytest.raises(MemoryBudgetExceededError):
            fleet.get('cold')
        # the serving model was never touched; the cold load rolled back
        assert fleet.loaded() == ['serving']
        assert fleet.budgeter.usage() == 80
        # once the serving model goes idle the cold one can displace it
        fleet.get('serving')._busy = False
        fleet.get('cold')
        assert fleet.loaded() == ['cold']

    def test_lease_blocks_eviction_until_block_exits(self):
        """The get()-then-forward race: a leased engine is never an
        eviction victim even while idle (busy() false); the same load
        succeeds once the lease is released."""
        fleet = MultiModelRegistry(mem_budget=100)
        fleet.add_model('a', lambda: _StubEngine(80), load=True)
        fleet.add_model('b', lambda: _StubEngine(80))
        with fleet.lease('a') as eng:
            assert not eng.busy()          # idle — but protected
            with pytest.raises(MemoryBudgetExceededError):
                fleet.get('b')
            assert fleet.loaded() == ['a']
        fleet.get('b')                     # lease released: evictable
        assert fleet.loaded() == ['b']

    def test_real_decode_engines_under_budget(self):
        """Acceptance leg: a second model loading under memory pressure
        evicts the cold model, never the one with in-flight streams."""
        pa, pb = _params(0), _params(31)
        # one engine is ~140KB resident: the budget fits one, never two
        fleet = MultiModelRegistry(mem_budget=200_000)

        def mk(params):
            return lambda: DecodeEngine(params, CFG, slots=2, pages=16,
                                        page_size=8, max_prompt=16,
                                        max_new_bound=32)

        try:
            fleet.add_model('a', mk(pa), load=True)
            eng_a = fleet.get('a')
            rng = np.random.RandomState(14)
            p = _prompt(rng)
            req = eng_a.submit_direct(p, max_new=32)   # 'a' is serving
            with pytest.raises(MemoryBudgetExceededError):
                fleet.add_model('b', mk(pb), load=True)
            assert fleet.loaded() == ['a']
            got = _wait_ok(req)                        # never dropped
            _assert_twin(got, _offline(pa, p, 32))
            deadline = time.time() + 5
            while eng_a.busy() and time.time() < deadline:
                time.sleep(0.01)
            fleet.get('b')                 # idle now: cold 'a' evicted
            assert fleet.loaded() == ['b']
        finally:
            fleet.close(30)


# --- gen cache satellites ---------------------------------------------------

class TestGenCacheStats:
    def test_hit_miss_counters(self):
        params = _params()
        rng = np.random.RandomState(15)
        p = rng.randint(0, 64, (1, 5)).astype(np.int32)
        T.gen_cache_stats(reset=True)
        T.generate(params, p, 3, CFG)
        s1 = T.gen_cache_stats()
        T.generate(params, p, 3, CFG)
        s2 = T.gen_cache_stats()
        assert s2['hit'] == s1['hit'] + 1
        assert s2['miss'] == s1['miss']

    def test_shrinking_env_enforced_on_next_call(self, monkeypatch):
        params = _params()
        rng = np.random.RandomState(16)
        monkeypatch.setenv('CXXNET_GEN_CACHE_MAX', '8')
        p1 = rng.randint(0, 64, (1, 5)).astype(np.int32)
        T.generate(params, p1, 3, CFG)
        T.generate(params, p1, 5, CFG)      # second size class
        assert len(T._GEN_CACHE) >= 2
        monkeypatch.setenv('CXXNET_GEN_CACHE_MAX', '1')
        T.generate(params, p1, 3, CFG)      # a HIT must still re-enforce
        assert len(T._GEN_CACHE) == 1

    def test_decode_report_exports_gen_cache(self, engine):
        line = engine.report('decode')
        assert 'decode-gen_cache.hit' in line
        assert 'decode-gen_cache.miss' in line


# --- lm file round-trip -----------------------------------------------------

def test_lm_params_roundtrip(tmp_path):
    params = _params(42)
    path = str(tmp_path / '0001.lm')
    save_lm_params(path, params)
    assert os.path.exists(path + '.crc32')
    loaded = load_lm_params(path)
    a = jax.tree.leaves(params)
    b = jax.tree.leaves(loaded)
    assert jax.tree.structure(params) == jax.tree.structure(loaded)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --- wrapper / C-ABI / CLI surface ------------------------------------------

class TestSurfaces:
    def test_capi_lm_serve_roundtrip(self, tmp_path):
        """The flat C-ABI decode surface: start from a saved .lm file,
        generate (twin-checked), stats, stop."""
        from cxxnet_tpu import capi
        params = _params(5)
        path = str(tmp_path / '0001.lm')
        save_lm_params(path, params)
        svc = capi.lm_serve_start(
            'vocab=64;d_model=32;heads=4;d_ff=48;stages=2;'
            f'slots=2;pages=32;page_size=8;max_prompt=16;max_new=16;'
            f'model_in={path}')
        try:
            rng = np.random.RandomState(17)
            prompt = rng.randint(0, 64, (6,)).astype(np.int32)
            toks = capi.lm_serve_generate(svc, memoryview(prompt), 6, 5)
            assert toks.dtype == np.int32 and toks.flags['C_CONTIGUOUS']
            _assert_twin(toks, _offline(params, prompt[None], 5))
            sampled = capi.lm_serve_generate(svc, memoryview(prompt), 6,
                                             5, temperature=0.9, seed=3)
            _assert_twin(sampled,
                         _offline(params, prompt[None], 5,
                                  temperature=0.9,
                                  rng=jax.random.PRNGKey(3)))
            assert 'decode-completed' in capi.lm_serve_stats(svc)
        finally:
            capi.lm_serve_stop(svc)

    def test_capi_net_serve_start_parses_fleet_options(self):
        from cxxnet_tpu import capi

        class NetStub:
            kw = None

            def serve_start(self, **kw):
                NetStub.kw = kw

        capi.net_serve_start(
            NetStub(), 'buckets=1:8;mem_budget=1000;'
                       'models=a:/tmp/x|b:/tmp/y')
        assert NetStub.kw['buckets'] == '1,8'
        assert NetStub.kw['mem_budget'] == 1000
        assert NetStub.kw['models'] == {'a': '/tmp/x', 'b': '/tmp/y'}

    def test_cli_decode_mode(self, tmp_path):
        """task=serve serve.mode=decode end to end: token streams in the
        pred file, the twin spot-check line, per-token stats."""
        conf = tmp_path / 'dec.conf'
        conf.write_text(
            'task = serve\n'
            'serve.mode = decode\n'
            'serve.lm = "vocab=64;d_model=32;heads=4;d_ff=48;stages=2"\n'
            'serve.slots = 2\n'
            'serve.pages = 32\n'
            'serve.page_size = 8\n'
            'serve.max_prompt = 12\n'
            'serve.max_new = 6\n'
            'serve.requests = 4\n'
            f'pred = {tmp_path}/toks.txt\n')
        r = _run_decode_cli(str(conf), str(tmp_path))
        assert 'decode twin check' in r.stdout
        assert 'finished serving 4 decode streams' in r.stdout
        assert 'decode-tokens' in r.stderr
        lines = (tmp_path / 'toks.txt').read_text().strip().splitlines()
        assert len(lines) == 4
        assert all(len(ln.split()) == 6 for ln in lines)


def _run_decode_cli(conf_path, cwd, *overrides, timeout=300):
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = repo + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run(
        [sys.executable, '-m', 'cxxnet_tpu.main', conf_path, *overrides],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, (r.stdout, r.stderr)
    return r


# --- e2e acceptance ---------------------------------------------------------

def test_e2e_concurrent_mixed_traffic_swap_and_budget():
    """The acceptance run: concurrent clients, mixed prompt lengths,
    staggered arrivals — every stream equals its offline twin; a
    hot-swap mid-decode drains with zero drops (in-flight streams finish
    on the old params, later ones decode under the new)."""
    pa, pa2 = _params(0), _params(99)
    svc = DecodeService(pa, CFG, slots=4, pages=64, page_size=8,
                        max_prompt=16, max_new_bound=32, deadline=120.0)
    results = []
    lock = threading.Lock()

    def client(cid):
        rng = np.random.RandomState(700 + cid)
        for i in range(3):
            p = _prompt(rng)
            key = jax.random.PRNGKey(cid * 17 + i)
            temp = 0.9 if (cid + i) % 2 else 0.0
            req = svc.submit_async(p, 8, temp, key if temp else None)
            svc.batcher.wait(req)
            with lock:
                results.append((p, temp, key, req))
            time.sleep(rng.uniform(0, 0.02))

    try:
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        svc.engine.swap_params(pa2, version='v2')   # mid-traffic
        for t in threads:
            t.join(120)
        assert len(results) == 12
        assert not any(r.error for *_, r in results), \
            'zero dropped requests across the swap'
        old_side = new_side = 0
        for p, temp, key, req in results:
            # drain semantics: a stream ran wholly under ONE params tree
            off_a = _offline(pa, p, 8, temperature=temp,
                             rng=key if temp else None)
            off_b = _offline(pa2, p, 8, temperature=temp,
                             rng=key if temp else None)
            got = np.asarray(req.result)
            if len(got) == len(off_a) and (got == off_a).all():
                old_side += 1
            else:
                _assert_twin(got, off_b)
                new_side += 1
        assert old_side >= 1 and new_side >= 1, \
            f'swap should split traffic (old={old_side}, new={new_side})'
        assert svc.engine.swap_count == 1
    finally:
        svc.close(30)
