"""graftshard suite: mesh-sharded decode serving (doc/serving.md
"Sharded serving").

The load-bearing claims:

* **sharding is BITWISE-invisible** — a ``serve.shard=tp:N`` engine
  column-shards every matmul weight and splits the KV page pool per
  attention head, yet every token stream equals the single-device
  offline ``transformer.generate`` twin at EVERY shard width, greedy
  and sampled, staggered or mid-join, through the prefix-share splice
  and the speculative-decode verify window,
* **disaggregated prefill is join-time-only** — ``serve.
  prefill_workers=N`` moves prompt prefill onto worker threads, and
  because admission already pins the join step, the streams stay twins
  no matter which thread prefilled them,
* **the memory story is per-device** — ``resident_bytes_per_device()``
  splits the closed-form ledger by actual shard placement, the
  ``hbm.*`` gauges bound it from live arrays, ``budget_drift()`` pins
  it to the compiled step's ``memory_analysis``, and the fleet
  ``MemoryBudgeter`` prices the MAX-loaded device,
* **data-parallel predict replicas are one engine** — a
  ``ReplicatedPredictEngine`` scores bitwise like its base engine,
  round-robins windows, and hot-swaps the whole fleet atomically under
  live traffic.

CPU-only: the 8-device virtual mesh from conftest.py stands in for a
TPU slice.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

import jax

from cxxnet_tpu import wrapper
from cxxnet_tpu.models import transformer as T
from cxxnet_tpu.parallel import mesh as mesh_mod
from cxxnet_tpu.serve import DynamicBatcher, ReplicatedPredictEngine
from cxxnet_tpu.serve.decode import DecodeEngine, DecodeService
from cxxnet_tpu.serve.engine import PredictEngine
from cxxnet_tpu.serve.registry import MemoryBudgeter, MultiModelRegistry

pytestmark = pytest.mark.shard

CFG = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                          d_ff=48, num_stages=2, seq_len=32, attn='local')
DCFG = T.TransformerConfig(vocab_size=64, d_model=16, num_heads=2,
                           d_ff=24, num_stages=1, seq_len=32, attn='local')


def _params(seed: int = 0, cfg=CFG):
    return T.init_params(np.random.RandomState(seed), cfg)


PARAMS = _params()
DRAFT = _params(1, DCFG)


def _prompt(rng, lo=1, hi=12):
    return rng.randint(0, CFG.vocab_size,
                       (1, int(rng.randint(lo, hi)))).astype(np.int32)


def _wait_ok(req, timeout=120):
    assert req.event.wait(timeout), 'request never completed'
    if req.error is not None:
        raise req.error
    return req.result


def _offline(params, prompt, max_new, temperature=0.0, rng=None,
             cfg=None):
    return np.asarray(T.generate(params, prompt, max_new,
                                 CFG if cfg is None else cfg,
                                 temperature=temperature, rng=rng))[0]


def _assert_twin(got, off):
    got = np.asarray(got)
    assert len(got) >= 1
    np.testing.assert_array_equal(got, off[:len(got)])


# --- serve.shard grammar and construction contract --------------------------

class TestShardContract:
    def test_parse_shard_grammar(self):
        assert mesh_mod.parse_shard('') == 1
        assert mesh_mod.parse_shard('tp:1') == 1
        assert mesh_mod.parse_shard('tp:4') == 4
        assert mesh_mod.parse_shard(' TP:2 ') == 2
        for bad in ('dp:2', 'tp:0', 'tp:-1', 'tp:x', '2'):
            with pytest.raises(ValueError):
                mesh_mod.parse_shard(bad)

    def test_heads_must_divide(self):
        with pytest.raises(ValueError, match='num_heads'):
            DecodeEngine(PARAMS, CFG, slots=2, pages=16, page_size=8,
                         max_prompt=16, max_new_bound=8, shard='tp:8')

    def test_single_slot_refused(self):
        """The bitwise-twin contract excludes degenerate one-row steps
        (XLA blocks the b*q==1 dot differently at one head/device)."""
        with pytest.raises(ValueError, match='slots >= 2'):
            DecodeEngine(PARAMS, CFG, slots=1, pages=16, page_size=8,
                         max_prompt=16, max_new_bound=8, shard='tp:2')

    def test_moe_refused(self):
        moe = dataclasses.replace(CFG, num_experts=2)
        with pytest.raises(ValueError, match='dense'):
            DecodeEngine(_params(cfg=moe), moe, slots=2, pages=16,
                         page_size=8, max_prompt=16, max_new_bound=8,
                         shard='tp:2')

    def test_mesh_wider_than_host_refused(self):
        with pytest.raises(ValueError, match='devices'):
            mesh_mod.decode_mesh(64)


# --- stream twins at every shard width --------------------------------------

@pytest.fixture(scope='module', params=['', 'tp:2', 'tp:4'])
def sharded(request):
    """One engine per shard width; offline twins run on the HOST copy
    (oracle_params) so the reference never compiles SPMD itself."""
    eng = DecodeEngine(PARAMS, CFG, slots=4, pages=64, page_size=8,
                       max_prompt=16, max_new_bound=32,
                       shard=request.param)
    yield request.param, eng
    eng.close(30)


class TestShardTwins:
    def test_greedy_staggered_mixed_lengths(self, sharded):
        shard, eng = sharded
        rng = np.random.RandomState(1)
        prompts = [_prompt(rng) for _ in range(5)]
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(eng.submit_direct(p, max_new=4 + i))
            time.sleep(0.01)            # later requests join mid-decode
        oracle = eng.oracle_params()
        for i, (p, r) in enumerate(zip(prompts, reqs)):
            got = _wait_ok(r)
            assert len(got) == 4 + i
            _assert_twin(got, _offline(oracle, p, 4 + i))

    def test_sampled_rng_schedule_matches_offline(self, sharded):
        shard, eng = sharded
        rng = np.random.RandomState(2)
        prompts = [_prompt(rng) for _ in range(3)]
        keys = [jax.random.PRNGKey(50 + i) for i in range(3)]
        reqs = [eng.submit_direct(p, max_new=6, temperature=0.8, rng=k)
                for p, k in zip(prompts, keys)]
        oracle = eng.oracle_params()
        for p, k, r in zip(prompts, keys, reqs):
            _assert_twin(_wait_ok(r),
                         _offline(oracle, p, 6, temperature=0.8, rng=k))

    def test_mid_join_stream(self, sharded):
        """A request admitted while another stream is mid-decode joins
        at a step boundary and both stay twins."""
        shard, eng = sharded
        rng = np.random.RandomState(3)
        p1, p2 = _prompt(rng), _prompt(rng)
        r1 = eng.submit_direct(p1, max_new=24)
        deadline = time.time() + 60
        while len(r1.tokens) < 3 and time.time() < deadline:
            time.sleep(0.002)           # provably mid-stream
        r2 = eng.submit_direct(p2, max_new=5)
        oracle = eng.oracle_params()
        _assert_twin(_wait_ok(r1), _offline(oracle, p1, 24))
        _assert_twin(_wait_ok(r2), _offline(oracle, p2, 5))

    def test_oracle_params_are_host_arrays_when_sharded(self, sharded):
        shard, eng = sharded
        leaves = jax.tree.leaves(eng.oracle_params())
        if shard:
            assert all(isinstance(l, np.ndarray) for l in leaves)
        else:
            assert eng.oracle_params() is eng.params


# --- the multipliers stay twins under the mesh ------------------------------

class TestShardMultipliers:
    def test_prefix_share_splice_bitwise_at_tp2(self):
        """A spliced prefix (tail-prefill over shared pages) is
        bitwise-invisible on the sharded gather path too."""
        eng = DecodeEngine(PARAMS, CFG, slots=2, pages=64, page_size=4,
                           max_prompt=16, max_new_bound=16,
                           prefix_share=16, shard='tp:2')
        try:
            rng = np.random.RandomState(5)
            stem = rng.randint(0, 64, (1, 13)).astype(np.int32)
            oracle = eng.oracle_params()
            off = _offline(oracle, stem, 6)
            _assert_twin(_wait_ok(eng.submit_direct(stem, max_new=6)),
                         off)
            hits0 = eng.stats.get('prefix_hit_pages')
            _assert_twin(_wait_ok(eng.submit_direct(stem, max_new=6)),
                         off)
            assert eng.stats.get('prefix_hit_pages') > hits0, \
                'second identical prompt must splice from the index'
        finally:
            eng.close(30)

    def test_spec_decode_twin_at_tp2(self):
        """Greedy speculative decoding under the mesh: the draft is
        replicated (bitwise-identical proposals on every device), the
        verify window runs sharded — streams equal offline greedy."""
        eng = DecodeEngine(PARAMS, CFG, slots=2, pages=64, page_size=8,
                           max_prompt=16, max_new_bound=16,
                           spec_k=3, draft=(DRAFT, DCFG), shard='tp:2')
        try:
            rng = np.random.RandomState(6)
            oracle = eng.oracle_params()
            for _ in range(2):
                p = _prompt(rng)
                _assert_twin(_wait_ok(eng.submit_direct(p, max_new=8)),
                             _offline(oracle, p, 8))
            assert eng.stats.get('spec_proposed') > 0
        finally:
            eng.close(30)


# --- disaggregated prefill ---------------------------------------------------

class TestDisaggregatedPrefill:
    def test_worker_prefill_streams_are_twins(self):
        """Prefill off the decode loop: mixed-length prompts admitted
        by two worker threads all equal their offline twins — the
        handoff at the join boundary is position-exact."""
        svc = DecodeService(PARAMS, CFG, slots=4, pages=64, page_size=8,
                            max_prompt=16, max_new_bound=16,
                            prefill_workers=2)
        try:
            names = [t.name for t in threading.enumerate()]
            assert sum(n.startswith('cxxnet-prefill-') for n in names) \
                == 2
            rng = np.random.RandomState(7)
            prompts = [_prompt(rng) for _ in range(8)]
            reqs = [svc.submit_async(p, 5) for p in prompts]
            for p, r in zip(prompts, reqs):
                svc.batcher.wait(r)
                assert r.error is None, r.error
                _assert_twin(r.result, _offline(PARAMS, p, 5))
            rep = svc.report()
            assert 'prefill_workers:2' in rep
        finally:
            svc.close(30)
        time.sleep(0.3)
        left = [t.name for t in threading.enumerate()
                if t.name.startswith('cxxnet-prefill-')]
        assert not left, f'prefill workers leaked: {left}'

    def test_disagg_composes_with_shard(self):
        """prefill_workers + tp:2 together (prefill compiles sharded
        programs from the worker thread via the thread-local
        shard_scope): still bitwise twins."""
        svc = DecodeService(PARAMS, CFG, slots=4, pages=64, page_size=8,
                            max_prompt=16, max_new_bound=16,
                            prefill_workers=2, shard='tp:2')
        try:
            rng = np.random.RandomState(8)
            prompts = [_prompt(rng) for _ in range(6)]
            reqs = [svc.submit_async(p, 5) for p in prompts]
            oracle = svc.engine.oracle_params()
            for p, r in zip(prompts, reqs):
                svc.batcher.wait(r)
                assert r.error is None, r.error
                _assert_twin(r.result, _offline(oracle, p, 5))
        finally:
            svc.close(30)

    def test_oversize_prompt_fails_typed_through_worker(self):
        """Admission errors classify identically on the worker path:
        the request carries the typed error, nothing hangs."""
        from cxxnet_tpu.runtime.faults import DecodeSlotsExhaustedError
        eng = DecodeEngine(PARAMS, CFG, slots=2, pages=16, page_size=8,
                           max_prompt=16, max_new_bound=8,
                           prefill_workers=1)
        try:
            rng = np.random.RandomState(9)
            req = eng.submit_direct(_prompt(rng), max_new=500)
            assert req.event.wait(30)
            assert isinstance(req.error, DecodeSlotsExhaustedError)
        finally:
            eng.close(30)


# --- per-device memory accounting -------------------------------------------

class TestShardAccounting:
    @pytest.fixture(scope='class')
    def tp2(self):
        eng = DecodeEngine(PARAMS, CFG, slots=2, pages=32, page_size=8,
                           max_prompt=16, max_new_bound=8, shard='tp:2')
        rng = np.random.RandomState(10)
        _wait_ok(eng.submit_direct(_prompt(rng), max_new=4))
        yield eng
        eng.close(30)

    def test_per_device_vector_reconciles_with_total(self, tp2):
        """Each device holds its OWN shard bytes: the vector sums to at
        least the closed-form total (replicated leaves count per
        device) and no single device carries the whole engine."""
        per = tp2.resident_bytes_per_device()
        total = tp2.resident_bytes()
        assert len(per) == 2 and all(b > 0 for b in per)
        assert sum(per) >= total
        assert max(per) < total
        # the head-sharded pool splits evenly: the devices balance
        assert abs(per[0] - per[1]) / max(per) < 0.05

    def test_report_carries_shard_gauges(self, tp2):
        rep = tp2.report()
        assert 'shard.tp:2' in rep
        assert 'shard.resident_bytes[d0]:' in rep
        assert 'shard.resident_bytes[d1]:' in rep

    def test_budget_drift_vs_compiled_step(self, tp2):
        """The compiler-truth cross-check holds for the sharded step:
        closed-form ledger vs memory_analysis argument bytes."""
        drift = tp2.budget_drift()
        if drift is None:
            pytest.skip('backend exposes no memory_analysis')
        assert abs(drift) < 0.05

    def test_hbm_gauges_bound_engine_bytes_per_device(self, tp2):
        """obs hbm.* live-array attribution sees each device's shard:
        bytes_in_use[dN] >= the engine's own bytes on that device."""
        from cxxnet_tpu.obs.programs import DeviceMemory
        from cxxnet_tpu.utils.metric import StatSet
        stats = StatSet()
        DeviceMemory().fill(stats)
        for i, b in enumerate(tp2.resident_bytes_per_device()):
            assert stats.get(f'bytes_in_use[d{i}]') >= b

    def test_unsharded_vector_is_the_scalar(self):
        eng = DecodeEngine(PARAMS, CFG, slots=2, pages=16, page_size=8,
                           max_prompt=16, max_new_bound=8)
        try:
            assert eng.resident_bytes_per_device() == \
                [eng.resident_bytes()]
        finally:
            eng.close(30)


class TestBudgeterPerDevice:
    def test_scalar_fleet_unchanged(self):
        b = MemoryBudgeter(100)
        b.account('a', 60)
        b.account('b', 50)
        assert b.usage() == 110
        assert b.usage_per_device() == [110]
        assert b.over_budget() == 10    # scalars all land on device 0

    def test_vector_prices_the_max_loaded_device(self):
        b = MemoryBudgeter(100)
        b.account('s', [90, 40, 40, 40])
        assert b.usage() == 210
        assert b.usage_per_device() == [90, 40, 40, 40]
        assert b.over_budget() == 0     # every device inside budget
        b.account('t', 30)              # scalar stacks onto device 0
        assert b.usage_per_device() == [120, 40, 40, 40]
        assert b.over_budget() == 20
        assert b.release('s') == 210
        assert b.usage_per_device() == [30]

    def test_resident_view_totals_vectors(self):
        b = MemoryBudgeter(0)
        b.account('s', (10, 20))
        b.account('p', 5)
        assert b.resident() == {'s': 30, 'p': 5}
        assert b.over_budget() == 0     # unbounded

    def test_fleet_load_accounts_per_device(self):
        """MultiModelRegistry._load feeds the budgeter the per-device
        vector when the engine exposes one."""
        class _ShardedStub:
            def resident_bytes(self):
                return 80

            def resident_bytes_per_device(self):
                return [40, 40]

            def busy(self):
                return False

            def close(self, timeout=None):
                pass
        fleet = MultiModelRegistry(mem_budget=50)
        fleet.add_model('s', _ShardedStub, load=True)
        # 80 total but 40/device: inside the per-device budget
        assert fleet.budgeter.usage() == 80
        assert fleet.budgeter.usage_per_device() == [40, 40]
        assert fleet.budgeter.over_budget() == 0
        rep = fleet.report()
        assert 'resident_bytes[d0]:40' in rep
        assert 'resident_bytes[d1]:40' in rep


# --- data-parallel predict replicas -----------------------------------------

NET_CFG = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 16
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 4
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
dev = cpu
eta = 0.1
"""


@pytest.fixture(scope='class')
def predict_net():
    net = wrapper.Net(dev='cpu', cfg=NET_CFG)
    net.set_param('seed', 0)
    net.init_model()
    return net


class TestReplicatedPredict:
    def test_replicas_score_bitwise_like_base(self, predict_net):
        base = PredictEngine(predict_net._trainer, (8,))
        rep = ReplicatedPredictEngine(predict_net._trainer, (8,),
                                      replicas=3)
        try:
            data = np.random.RandomState(0).randn(5, 1, 1, 8) \
                .astype(np.float32)
            s0 = base.predict_scores(data)
            for _ in range(3):          # every replica takes a turn
                np.testing.assert_array_equal(
                    rep.predict_scores(data), s0)
            per = rep.resident_bytes_per_device()
            assert len(per) == 3 and sum(per) == rep.resident_bytes()
            assert rep.compile_count == 3   # one bucket x 3 replicas
        finally:
            rep.close(10)

    def test_batcher_round_robin_is_bitwise(self, predict_net):
        from cxxnet_tpu.utils.metric import StatSet
        rep = ReplicatedPredictEngine(predict_net._trainer, (8,),
                                      replicas=2, stats=StatSet())
        bat = DynamicBatcher(rep, max_queue=64, max_wait=0.001,
                             deadline=30.0, stats=rep.stats)
        try:
            data = np.random.RandomState(1).randn(6, 1, 1, 8) \
                .astype(np.float32)
            base = rep.engines[0].predict_scores(data)
            # submit-then-wait: one coalesced window per request, so
            # strict round-robin provably rotates replicas
            for i in range(6):
                r = bat.submit_async(data[i:i + 1])
                np.testing.assert_array_equal(bat.wait(r),
                                              base[i:i + 1])
            rows = sum(rep.stats.get(f'replica_rows[r{i}]')
                       for i in range(2))
            assert rows >= 6
            assert all(rep.stats.get(f'replica_rows[r{i}]') > 0
                       for i in range(2)), 'dispatch never rotated'
        finally:
            bat.close()
            rep.close(10)

    def test_fleet_swap_is_atomic_under_traffic(self, predict_net):
        """Hot-swap drains all replicas and flips them together: no
        request errors, post-swap scores change everywhere at once."""
        from cxxnet_tpu.utils.metric import StatSet
        rep = ReplicatedPredictEngine(predict_net._trainer, (8,),
                                      replicas=2, stats=StatSet())
        bat = DynamicBatcher(rep, max_queue=256, max_wait=0.001,
                             deadline=30.0, stats=rep.stats)
        data = np.random.RandomState(2).randn(4, 1, 1, 8) \
            .astype(np.float32)
        p2 = jax.tree.map(lambda l: np.asarray(l) * 1.5,
                          predict_net._trainer.params)
        stop = threading.Event()
        errs = []

        def pound():
            while not stop.is_set():
                try:
                    bat.submit(data)
                except Exception as e:      # noqa: BLE001 - recorded
                    errs.append(e)
                    return

        thr = [threading.Thread(target=pound) for _ in range(3)]
        try:
            for t in thr:
                t.start()
            for v in range(1, 4):
                rep.swap_params(p2 if v % 2 else
                                predict_net._trainer.params, version=v)
            stop.set()
            for t in thr:
                t.join(30)
            assert not errs, errs[:2]
            assert rep.swap_count == 3
            assert rep.version == 3
            # every replica serves the LAST swap's params
            s_each = [e.predict_scores(data) for e in rep.engines]
            np.testing.assert_array_equal(s_each[0], s_each[1])
        finally:
            stop.set()
            bat.close()
            rep.close(10)
        time.sleep(0.3)
        left = [t.name for t in threading.enumerate()
                if t.name.startswith('cxxnet-replica-')]
        assert not left, f'replica workers leaked: {left}'

    def test_replicas_validate(self, predict_net):
        with pytest.raises(ValueError):
            ReplicatedPredictEngine(predict_net._trainer, (8,),
                                    replicas=0)
        with pytest.raises(ValueError, match='devices'):
            ReplicatedPredictEngine(predict_net._trainer, (8,),
                                    replicas=999)
