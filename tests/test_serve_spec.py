"""Prefix-shared paged KV cache + greedy speculative decoding suite
(serve/decode.py "Prefix sharing" / "Speculative decoding").

The load-bearing claims:

* **prefix sharing is BITWISE-invisible** — a stream whose prompt
  prefix was spliced from the content-addressed index equals the same
  request served unshared equals its offline ``transformer.generate``
  twin, greedy and sampled, at any join time and pad width (the tail
  prefill is pinned bitwise-equal to the full prefill row-for-row),
* **refcounts protect shared pages** — preempting or expiring a stream
  never frees a page another slot (or the index) still references, and
  ``resident_bytes`` counts each physical page once no matter how many
  page tables reference it,
* **greedy spec decode is TOKEN-EQUAL to the target alone** — every
  accepted token is the target's own greedy pick at its position, so
  the stream equals offline greedy ``generate`` for every seed tested
  (on every ``serve.dtype`` tier; the verify window's float
  reassociation perturbs logits at the ulp level, which these twins
  police per seed).
"""

import os
import threading
import time

import numpy as np
import pytest

import jax

from cxxnet_tpu.models import transformer as T
from cxxnet_tpu.runtime.faults import (DecodePagesExhaustedError,
                                       PrefixIndexFullError)
from cxxnet_tpu.serve.batcher import DynamicBatcher, ServeRequest
from cxxnet_tpu.serve.decode import DecodeEngine
from cxxnet_tpu.serve.registry import MultiModelRegistry

pytestmark = pytest.mark.serve_spec

CFG = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                          d_ff=48, num_stages=2, seq_len=32, attn='local')
DCFG = T.TransformerConfig(vocab_size=64, d_model=16, num_heads=2,
                           d_ff=24, num_stages=1, seq_len=32, attn='local')


def _params(seed: int = 0, cfg=CFG):
    return T.init_params(np.random.RandomState(seed), cfg)


PARAMS = _params()
DRAFT = _params(1, DCFG)


def _wait_ok(req, timeout=120):
    assert req.event.wait(timeout), 'request never completed'
    if req.error is not None:
        raise req.error
    return req.result


def _offline(prompt, max_new, temperature=0.0, rng=None, params=None,
             cfg=None):
    return np.asarray(T.generate(
        PARAMS if params is None else params, prompt, max_new,
        CFG if cfg is None else cfg, temperature=temperature,
        rng=rng))[0]


def _assert_twin(got, off):
    got = np.asarray(got)
    assert len(got) >= 1
    np.testing.assert_array_equal(got, off[:len(got)])


# --- the tail prefill is bitwise-equal to the full prefill ------------------

class TestTailPrefill:
    @pytest.mark.parametrize('w_pad,s0', [(0, 16), (3, 13)])
    def test_tail_rows_and_logits_bitwise_equal_full_prefill(self, w_pad,
                                                             s0):
        rng = np.random.RandomState(7)
        prompt = rng.randint(0, 64, (1, s0)).astype(np.int32)
        padded = np.pad(prompt, ((0, 0), (w_pad, 0)))
        ks, vs, lg = jax.jit(
            lambda p, t, w: T.prefill_kv(p, t, w, CFG))(
                PARAMS, padded, np.int32(w_pad))
        ks, vs, lg = np.asarray(ks), np.asarray(vs), np.asarray(lg)
        t0 = 8                      # one full 8-token page shared
        tks, tvs, tlg = jax.jit(
            lambda p, pk, pv, tl, w: T.prefill_tail_kv(p, pk, pv, tl, w,
                                                       CFG))(
            PARAMS, ks[:, :, :t0], vs[:, :, :t0], padded[:, t0:],
            np.int32(w_pad))
        np.testing.assert_array_equal(np.asarray(tks), ks[:, :, t0:])
        np.testing.assert_array_equal(np.asarray(tvs), vs[:, :, t0:])
        np.testing.assert_array_equal(np.asarray(tlg), lg)


# --- verify window: dense, paged-flash, token-equality ----------------------

class TestVerifyStep:
    def _prefilled(self, S=2, s0=8):
        rng = np.random.RandomState(3)
        prompt = rng.randint(0, 64, (S, s0)).astype(np.int32)
        ks, vs, lg = jax.jit(
            lambda p, t, w: T.prefill_kv(p, t, w, CFG))(
                PARAMS, prompt, np.int32(0))
        hd = CFG.d_model // CFG.num_heads
        Tlen = 32
        kc = np.zeros((CFG.num_stages, S, Tlen, CFG.num_heads, hd),
                      np.float32)
        vc = np.zeros_like(kc)
        kc[:, :, :s0] = np.asarray(ks)
        vc[:, :, :s0] = np.asarray(vs)
        tok0 = np.asarray(np.asarray(lg).argmax(-1), np.int32)
        return kc, vc, tok0, s0

    def test_verify_window_token_equal_sequential_decode(self):
        """The greedy chain through one K=4 verify window equals K
        sequential decode_steps' argmax chain (the spec-decode
        token-equality kernel claim), and the K/V rows land where the
        sequential steps put them (allclose at ulp scale; the STREAM
        equality tests below are the binding contract)."""
        kc, vc, tok0, s0 = self._prefilled()
        S, K = kc.shape[1], 4
        t = np.full(S, s0, np.int32)
        w = np.zeros(S, np.int32)
        kcs, vcs = jax.numpy.asarray(kc), jax.numpy.asarray(vc)
        tok = jax.numpy.asarray(tok0)
        step = jax.jit(lambda p, tk, kk, vv, tt, ww: T.decode_step(
            p, CFG, tk, kk, vv, tt, ww))
        window, seq_argmax = [np.asarray(tok0)], []
        for k in range(K):
            lg, kcs, vcs, _, _ = step(PARAMS, tok, kcs, vcs, t + k, w)
            tok = lg.argmax(-1).astype(jax.numpy.int32)
            seq_argmax.append(np.asarray(tok))
            if k < K - 1:
                window.append(np.asarray(tok))
        toks = np.stack(window, axis=1)
        vl, kc2, vc2, knew, vnew = jax.jit(
            lambda p, tk, kk, vv, tt, ww: T.verify_step(
                p, CFG, tk, kk, vv, tt, ww))(
            PARAMS, toks, jax.numpy.asarray(kc), jax.numpy.asarray(vc),
            t, w)
        np.testing.assert_array_equal(
            np.asarray(vl).argmax(-1), np.stack(seq_argmax, axis=1))
        np.testing.assert_allclose(
            np.asarray(kc2)[:, :, s0:s0 + K], np.asarray(knew),
            rtol=0, atol=0)
        np.testing.assert_allclose(
            np.asarray(kc2)[:, :, s0:s0 + K],
            np.asarray(kcs)[:, :, s0:s0 + K], atol=1e-5)

    def test_flash_verify_bitwise_equal_dense(self):
        """paged_flash_verify (interpret mode) == gather + verify_step,
        bitwise, over a shuffled physical page pool."""
        kc, vc, tok0, s0 = self._prefilled()
        S, ps, Tlen = kc.shape[1], 8, 32
        pp = Tlen // ps
        hd = CFG.d_model // CFG.num_heads
        n_phys = S * pp + 3
        kpool = np.zeros((CFG.num_stages, n_phys, ps, CFG.num_heads, hd),
                         np.float32)
        vpool = np.zeros_like(kpool)
        phys = np.random.RandomState(9).permutation(
            np.arange(1, n_phys))[:S * pp]
        table = phys.reshape(S, pp).astype(np.int32)
        for b in range(S):
            for lp in range(pp):
                kpool[:, table[b, lp]] = kc[:, b, lp * ps:(lp + 1) * ps]
                vpool[:, table[b, lp]] = vc[:, b, lp * ps:(lp + 1) * ps]
        toks = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
        t = np.full(S, s0, np.int32)
        w = np.zeros(S, np.int32)
        dl, _, _, _, _ = jax.jit(
            lambda p, tk, kk, vv, tt, ww: T.verify_step(
                p, CFG, tk, kk, vv, tt, ww))(
            PARAMS, toks, jax.numpy.asarray(kc), jax.numpy.asarray(vc),
            t, w)
        fl, _, _ = jax.jit(
            lambda p, tk, kk, vv, tb, tt, ww: T.verify_step_paged(
                p, CFG, tk, kk, vv, tb, tt, ww))(
            PARAMS, toks, jax.numpy.asarray(kpool),
            jax.numpy.asarray(vpool), jax.numpy.asarray(table), t, w)
        np.testing.assert_array_equal(np.asarray(fl), np.asarray(dl))


# --- prefix sharing: stream equality + index mechanics ----------------------

class TestPrefixSharing:
    def _engine(self, **kw):
        kw.setdefault('slots', 4)
        kw.setdefault('pages', 64)
        kw.setdefault('page_size', 8)
        kw.setdefault('max_prompt', 16)
        kw.setdefault('max_new_bound', 32)
        kw.setdefault('prefix_share', 16)
        return DecodeEngine(PARAMS, CFG, **kw)

    def test_shared_streams_equal_unshared_equal_offline(self):
        """The acceptance-criteria grid: greedy and sampled, staggered
        joins, mixed prefix lengths, w in {0, 3} — shared streams ==
        unshared streams == offline twins, bitwise."""
        rng = np.random.RandomState(11)
        base = rng.randint(0, 64, (1, 16)).astype(np.int32)   # w=0
        base13 = np.concatenate(
            [base[:, :12], rng.randint(0, 64, (1, 1))], axis=1)  # w=3
        keyed = jax.random.PRNGKey(5)
        work = [
            (base.copy(), 8, 0.0, None),
            (base.copy(), 6, 0.0, None),
            (base13.copy(), 8, 0.0, None),
            (base13.copy(), 8, 0.9, keyed),
            (np.concatenate([base[:, :8],
                             rng.randint(0, 64, (1, 4))], axis=1),
             8, 0.0, None),                       # shorter shared prefix
        ]
        shared = self._engine()
        unshared = self._engine(prefix_share=0)
        try:
            got = {}
            for name, eng in (('on', shared), ('off', unshared)):
                reqs = []
                for i, (p, mn, temp, key) in enumerate(work):
                    reqs.append(eng.submit_direct(
                        p, max_new=mn, temperature=temp, rng=key))
                    if i % 2:
                        time.sleep(0.02)          # staggered joins
                got[name] = [np.asarray(_wait_ok(r)) for r in reqs]
            for (p, mn, temp, key), g_on, g_off in zip(
                    work, got['on'], got['off']):
                off = _offline(p, mn, temperature=temp, rng=key)
                _assert_twin(g_on, off)
                np.testing.assert_array_equal(g_on, g_off)
            assert shared.stats.get('prefix_hits') >= 2
            assert shared.stats.get('prefix_published') >= 2
            assert unshared.stats.get('prefix_hits') == 0
        finally:
            shared.close(30)
            unshared.close(30)

    def test_resident_bytes_counts_shared_pages_once(self):
        """Two slots sharing a prefix report the same footprint as one
        (the PR 10 closed-form pool accounting stays refcount-correct),
        and the second stream's private page draw is only its tail."""
        eng = self._engine(max_new_bound=8)
        try:
            p = np.arange(16, dtype=np.int32)[None]
            rb_zero = eng.resident_bytes()
            r1 = eng.submit_direct(p, max_new=8)
            _wait_ok(r1)
            rb_one = eng.resident_bytes()
            r2 = eng.submit_direct(p.copy(), max_new=8)
            _wait_ok(r2)
            # the pool is ONE allocation: footprint is invariant to how
            # many page tables share its pages
            assert eng.resident_bytes() == rb_one == rb_zero
            assert eng.stats.get('prefix_hits') == 1
            with eng._cond:
                used = eng.n_pages - 1 - len(eng._free_pages)
            # both streams retired: only the 2 published prefix pages
            # stay resident (held once by the index, never per sharer)
            assert used == 2
        finally:
            eng.close(30)

    def test_preemption_never_frees_shared_pages_and_replay_twin(self):
        """Pool-dry preemption of a stream holding shared pages
        decrements refcounts only; the survivor (sharing the same
        physical prefix pages) finishes bitwise-intact, and the victim's
        replay after readmission is token-equal."""
        # tiny pool: 2 prefix pages (shared) + index ref; two streams
        # decoding far enough to exhaust the rest
        eng = DecodeEngine(PARAMS, CFG, slots=2, pages=8, page_size=8,
                           max_prompt=16, max_new_bound=32,
                           prefix_share=4)
        try:
            p = np.arange(16, dtype=np.int32)[None]
            off = _offline(p, 24)
            r1 = eng.submit_direct(p, max_new=24)
            time.sleep(0.1)                       # r1 grabs pages first
            r2 = eng.submit_direct(p.copy(), max_new=24)
            res1 = _wait_ok(r1)
            _assert_twin(res1, off)
            with pytest.raises(DecodePagesExhaustedError):
                _wait_ok(r2)
            assert eng.stats.get('prefix_hits') == 1
            assert eng.stats.get('shed_pages') == 1
            # replay after readmission: token-equal (and hits again)
            r3 = eng.submit_direct(p.copy(), max_new=24)
            _assert_twin(_wait_ok(r3), off)
            with eng._cond:
                refs = eng._page_refs.copy()
                free = set(eng._free_pages)
            # no page is both free and referenced
            assert all(refs[pg] == 0 for pg in free)
        finally:
            eng.close(30)

    def test_pool_dry_reclaim_never_frees_probed_hit_pages(self):
        """Regression (PR 12 review): when the pool is dry at admission
        and the only reclaimable index pages ARE the ones the request
        just probed as hits, reclaim must skip them — freeing one would
        alias the same physical page as both a shared prefix page and a
        fresh allocation, and the tail writes would clobber the prefix
        rows the stream reads (observed live as a twin divergence)."""
        eng = DecodeEngine(PARAMS, CFG, slots=2, pages=10, page_size=4,
                           max_prompt=16, max_new_bound=5,
                           prefix_share=8)
        try:
            a = np.arange(16, dtype=np.int32)[None]
            _assert_twin(_wait_ok(eng.submit_direct(a, max_new=4)),
                         _offline(a, 4))  # publishes 4 pages, finishes
            # a cold stream drains the remaining pool and KEEPS
            # decoding: A's pages are now the only reclaimable
            # (refcount-1) entries while C is admitted
            b = np.arange(16, 32, dtype=np.int32)[None]
            rb = eng.submit_direct(b, max_new=5)
            # C hits A's prefix with the pool dry — its admission must
            # wait for B rather than reclaim its own hit pages
            got = _wait_ok(eng.submit_direct(a.copy(), max_new=5))
            _assert_twin(got, _offline(a, 5))
            _assert_twin(_wait_ok(rb), _offline(b, 5))
            assert eng.stats.get('prefix_hits') >= 1
        finally:
            eng.close(30)

    def test_index_eviction_frees_pages_and_full_error_recorded(self):
        """LRU eviction keeps the index at its page cap; a prompt whose
        shareable pages exceed the whole cap records the typed
        PrefixIndexFullError outcome and serves unshared."""
        eng = self._engine(prefix_share=1)   # cap < 2 full pages
        try:
            p = np.arange(16, dtype=np.int32)[None]   # 2 shareable pages
            _wait_ok(eng.submit_direct(p, max_new=4))
            assert eng.stats.get('prefix_index_full') == 1
            assert eng.stats.get('prefix_published') == 0
            # a one-page prompt (s0b=8) fits the cap; a second distinct
            # one LRU-evicts it and the evictee's page goes back to the
            # pool (refcount zero)
            q1 = np.arange(8, dtype=np.int32)[None]
            q2 = np.arange(8, 16, dtype=np.int32)[None]
            _wait_ok(eng.submit_direct(q1, max_new=4))
            assert eng.stats.get('prefix_published') == 1
            _wait_ok(eng.submit_direct(q2, max_new=4))
            assert eng.stats.get('prefix_published') == 2
            with eng._cond:
                assert len(eng._prefix) == 1
                assert (eng._page_refs[1:] > 0).sum() == 1
        finally:
            eng.close(30)
        err = PrefixIndexFullError(3, 1)
        assert err.needed == 3 and err.cap == 1

    def test_swap_drains_and_clears_prefix_index(self):
        """A param hot-swap releases every index reference (stale keys
        would leak pages) and post-swap streams twin the NEW params."""
        eng = self._engine()
        try:
            p = np.arange(16, dtype=np.int32)[None]
            _wait_ok(eng.submit_direct(p, max_new=4))
            with eng._cond:
                assert len(eng._prefix) >= 1
            new_params = _params(9)
            eng.swap_params(new_params, version=9)
            with eng._cond:
                assert len(eng._prefix) == 0
                assert (eng._page_refs[1:] == 0).all()
                assert len(eng._free_pages) == eng.n_pages - 1
            r = eng.submit_direct(p.copy(), max_new=6)
            _assert_twin(_wait_ok(r), _offline(p, 6, params=new_params))
        finally:
            eng.close(30)

    def test_prefill_cost_prices_hits_at_their_tail(self):
        eng = self._engine()
        try:
            p = np.arange(16, dtype=np.int32)[None]
            req = ServeRequest(p, 30.0)
            assert eng.prefill_cost(req) == 16       # cold: full prompt
            _wait_ok(eng.submit_direct(p, max_new=4))
            assert eng.prefill_cost(ServeRequest(p, 30.0)) == 8  # tail
        finally:
            eng.close(30)

    def test_report_exports_pool_and_prefix_gauges(self):
        eng = self._engine()
        try:
            p = np.arange(16, dtype=np.int32)[None]
            _wait_ok(eng.submit_direct(p, max_new=4))
            line = eng.report('px')
            for key in ('px-free_pages', 'px-free_pages_min',
                        'px-pages_used', 'px-pages_shared',
                        'px-prefix_index_pages', 'px-prefix_published'):
                assert key in line, line
        finally:
            eng.close(30)


# --- batcher admission pricing ----------------------------------------------

class TestBatcherCost:
    def test_cost_budget_closes_window(self):
        """With a cost_fn, the coalescing window closes before the
        budget is breached (order preserved), and the first request
        always rides."""
        executed = []
        gate = threading.Event()

        class Stub:
            buckets = (8,)

            def predict_scores(self, data):
                gate.wait(5)
                executed.append(data.shape[0])
                return np.zeros((data.shape[0], 1), np.float32)

        b = DynamicBatcher(Stub(), max_wait=0.2, deadline=10.0,
                           cost_fn=lambda r: int(r.meta['cost']),
                           max_cost=10)
        try:
            reqs = [b.submit_async(np.zeros((1, 1), np.float32),
                                   meta={'cost': c})
                    for c in (6, 3, 9, 1)]
            gate.set()
            for r in reqs:
                b.wait(r)
            # 6+3 fit the 10-cost budget; 9 starts the next window
            assert executed[0] == 2 and sum(executed) == 4
            assert b.stats.get('cost_closed') >= 1
        finally:
            b.close(10)

    def test_max_cost_requires_cost_fn(self):
        class Stub:
            buckets = (4,)
        with pytest.raises(ValueError):
            DynamicBatcher(Stub(), max_cost=5)


# --- speculative decoding ---------------------------------------------------

class TestSpecDecode:
    def _engine(self, draft=(DRAFT, DCFG), dtype='f32', **kw):
        kw.setdefault('slots', 3)
        kw.setdefault('pages', 64)
        kw.setdefault('page_size', 8)
        kw.setdefault('max_prompt', 16)
        kw.setdefault('max_new_bound', 16)
        kw.setdefault('spec_k', 4)
        return DecodeEngine(PARAMS, CFG, draft=draft, dtype=dtype, **kw)

    @pytest.mark.parametrize('seed', [5, 23, 71])
    def test_spec_streams_token_equal_target_greedy(self, seed):
        """Spec-decoded streams == target-only greedy == offline
        generate, per seed, with a cold (disagreeing) draft, staggered
        joins and mixed prompt lengths."""
        eng = self._engine()
        try:
            rng = np.random.RandomState(seed)
            reqs = []
            for i in range(5):
                p = rng.randint(0, 64,
                                (1, int(rng.randint(2, 14)))).astype(
                                    np.int32)
                reqs.append((p, eng.submit_direct(p, max_new=10)))
                if i % 2:
                    time.sleep(0.02)
            for p, r in reqs:
                _assert_twin(_wait_ok(r), _offline(p, 10))
            assert eng.stats.get('spec_steps') >= 1
            assert eng.stats.get('spec_proposed') >= 3
        finally:
            eng.close(30)

    def test_twin_draft_high_acceptance(self):
        """A draft sharing the target's params accepts most proposals
        (the self-speculation upper bound) — and stays token-equal."""
        eng = self._engine(draft=(PARAMS, CFG))
        try:
            p = np.asarray([[1, 2, 3, 4, 5]], np.int32)
            _assert_twin(_wait_ok(eng.submit_direct(p, max_new=12)),
                         _offline(p, 12))
            acc = (eng.stats.get('spec_accepted')
                   / max(1.0, eng.stats.get('spec_proposed')))
            assert acc >= 0.5, acc
            assert 'spec_accept_rate' in eng.report('sd')
        finally:
            eng.close(30)

    def test_int8_tier_token_equal(self):
        """Spec decode on the quantized tier: the oracle is generate()
        over the ENGINE's stored (quantized) tree — exact, per seed."""
        eng = self._engine(dtype='int8')
        try:
            for seed in (3, 4):
                p = np.random.RandomState(seed).randint(
                    0, 64, (1, 6)).astype(np.int32)
                got = _wait_ok(eng.submit_direct(p, max_new=8))
                _assert_twin(got, np.asarray(T.generate(
                    eng.params, p, 8, eng.cfg))[0])
        finally:
            eng.close(30)

    def test_sampled_stream_pauses_spec_exactly(self):
        """A sampled stream in a spec engine keeps its exact per-key RNG
        schedule (spec pauses while it is live — never approximates),
        and greedy streams riding the same steps stay token-equal."""
        eng = self._engine()
        try:
            p = np.asarray([[3, 1, 4, 1, 5, 9]], np.int32)
            key = jax.random.PRNGKey(42)
            r1 = eng.submit_direct(p, max_new=8, temperature=0.8,
                                   rng=key)
            r2 = eng.submit_direct(p.copy(), max_new=8)
            _assert_twin(_wait_ok(r1),
                         _offline(p, 8, temperature=0.8, rng=key))
            _assert_twin(_wait_ok(r2), _offline(p, 8))
        finally:
            eng.close(30)

    def test_spec_composes_with_prefix_share_and_flash(self):
        eng = self._engine(prefix_share=8, flash_decode=1)
        try:
            p = np.arange(16, dtype=np.int32)[None]
            off = _offline(p, 10)
            _assert_twin(_wait_ok(eng.submit_direct(p, max_new=10)), off)
            _assert_twin(_wait_ok(eng.submit_direct(p.copy(),
                                                    max_new=10)), off)
            assert eng.stats.get('prefix_hits') == 1
        finally:
            eng.close(30)

    def test_spec_k_without_draft_rejected(self):
        with pytest.raises(ValueError):
            DecodeEngine(PARAMS, CFG, spec_k=4)

    def test_draft_vocab_mismatch_rejected(self):
        bad = T.TransformerConfig(vocab_size=32, d_model=16, num_heads=2,
                                  d_ff=24, num_stages=1, attn='local')
        with pytest.raises(ValueError):
            DecodeEngine(PARAMS, CFG, spec_k=2,
                         draft=(_params(1, bad), bad))


# --- draft hot-swap through the registry ------------------------------------

class TestDraftRegistry:
    def test_attach_draft_hot_swaps_and_streams_unchanged(self, tmp_path):
        """A new draft checkpoint dropped into the watched dir swaps in
        through the verify/blacklist machinery — and cannot change a
        stream, only the acceptance rate."""
        from cxxnet_tpu.serve.decode import (LM_PATTERN, lm_loader,
                                             save_lm_params)
        fleet = MultiModelRegistry()
        eng_holder = {}

        def factory():
            eng = DecodeEngine(PARAMS, CFG, slots=2, pages=32,
                               page_size=8, max_prompt=16,
                               max_new_bound=16, spec_k=3,
                               draft=(DRAFT, DCFG))
            eng_holder['eng'] = eng
            return eng

        fleet.add_model('lm', factory, load=True)
        draft_dir = tmp_path / 'drafts'
        draft_dir.mkdir()
        reg = fleet.attach_draft('lm', str(draft_dir),
                                 pattern=LM_PATTERN, loader=lm_loader)
        try:
            eng = eng_holder['eng']
            p = np.asarray([[1, 2, 3, 4, 5, 6]], np.int32)
            off = _offline(p, 8)
            _assert_twin(_wait_ok(eng.submit_direct(p, max_new=8)), off)
            assert fleet.poll_once() == 0          # nothing to adopt
            # publish a new draft (= the target tree: acceptance rises)
            save_lm_params(str(draft_dir / '0001.lm'), PARAMS)
            # the adapter quantizes/validates against the DRAFT
            # structure: the target tree differs -> REJECTED, old draft
            # keeps proposing
            assert fleet.poll_once() == 0
            assert 'REJECTED' in reg.states()
            save_lm_params(str(draft_dir / '0002.lm'), _params(8, DCFG))
            assert fleet.poll_once() == 1
            assert eng.draft_version == 2
            _assert_twin(_wait_ok(eng.submit_direct(p.copy(),
                                                    max_new=8)), off)
        finally:
            fleet.close(30)


# --- CLI / capi surfaces ----------------------------------------------------

class TestSurfaces:
    def test_capi_lm_serve_spec_keys(self):
        from cxxnet_tpu import capi
        svc = capi.lm_serve_start(
            'vocab=64;d_model=32;heads=4;d_ff=48;stages=2;'
            'slots=2;pages=32;page_size=8;max_prompt=16;max_new=16;'
            'prefix_share=8;spec_k=3;'
            'draft.d_model=16;draft.heads=2;draft.d_ff=24;'
            'draft.stages=1;draft.seed=1')
        try:
            assert svc.engine._spec_k == 3
            assert svc.engine._prefix_cap == 8
            assert svc.engine._draft_cfg.vocab_size == 64
            prompt = np.arange(6, dtype=np.int32)
            toks = capi.lm_serve_generate(svc, memoryview(prompt), 6, 5)
            off = np.asarray(T.generate(
                svc.engine.params, prompt[None], 5, svc.engine.cfg))[0]
            _assert_twin(toks, off)
            assert 'decode-completed' in capi.lm_serve_stats(svc)
        finally:
            capi.lm_serve_stop(svc)

    def test_cli_decode_prefix_spec(self, tmp_path):
        """task=serve serve.mode=decode with prefix sharing + spec
        decode end to end: the drive's built-in twin check passes and
        the stderr stats carry the new gauges."""
        import subprocess
        import sys
        conf = tmp_path / 'dec.conf'
        conf.write_text(
            'task = serve\n'
            'serve.mode = decode\n'
            'serve.lm = "vocab=64;d_model=32;heads=4;d_ff=48;stages=2"\n'
            'serve.draft = "d_model=16;heads=2;d_ff=24;stages=1;seed=1"\n'
            'serve.spec_k = 3\n'
            'serve.prefix_share = 8\n'
            'serve.slots = 2\n'
            'serve.pages = 32\n'
            'serve.page_size = 8\n'
            'serve.max_prompt = 16\n'
            'serve.max_new = 8\n'
            'serve.requests = 6\n'
            f'pred = {tmp_path / "toks.txt"}\n')
        r = subprocess.run(
            [sys.executable, '-m', 'cxxnet_tpu.main', str(conf)],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
        assert r.returncode == 0, r.stdout + r.stderr
        assert 'decode twin check: 3 streams equal' in r.stdout
        assert 'spec_k=3' in r.stdout
        assert 'decode-free_pages_min' in r.stderr
        lines = (tmp_path / 'toks.txt').read_text().strip().splitlines()
        assert len(lines) == 6
