"""graftwatch SLO suite (``-m slo``, doc/observability.md "SLOs and
burn rates" / "Fleet view").

The load-bearing claims:

* gauge history is bounded, windowed, and reduces (rate/quantiles)
  deterministically over explicit monotonic timestamps,
* the ``slo.<name>=`` grammar parses into typed specs, and the engine
  evaluates multi-window burn rates into OK / AT_RISK / BREACHED with
  the no-flap property (a blip is AT_RISK, only a sustained violation
  BREACHES, an ongoing breach counts once),
* a breach records the typed ``SLOBreachError`` kind and the armed
  flight recorder ships a postmortem containing the breaching window's
  samples and verdict history — proven through a real FaultPlan drill,
* the freshness SLO runs through the generic engine behavior-equal
  (typed ``FreshnessSLOError``, historical log kind, strict raise),
* ``/slos`` serves typed verdicts and ``/healthz`` reports
  ``degraded`` (still 200) while any SLO is BREACHED,
* per-rank ObsServers bind ephemeral ports without collision and the
  fleet scraper/merger survives a rank's death (unit level here; the
  real ≥2-rank acceptance run lives in test_elastic.py, ``-m dist``).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.obs import TelemetryHub, get_hub, install_hub
from cxxnet_tpu.obs.endpoints import ObsServer
from cxxnet_tpu.obs.fleet import (FleetScraper, FleetServer,
                                  merge_chrome_traces, merge_metrics,
                                  parse_gauges)
from cxxnet_tpu.obs.history import GaugeHistory, GaugeSampler
from cxxnet_tpu.obs.slo import (AT_RISK, BREACHED, OK, SLOEngine,
                                SLOSpec)
from cxxnet_tpu.runtime import faults
from cxxnet_tpu.utils.metric import StatSet
from cxxnet_tpu.utils.thread_buffer import ThreadBuffer

pytestmark = pytest.mark.slo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def hub():
    h = TelemetryHub(ring_events=256)
    prev = install_hub(h)
    yield h
    h.disarm()
    install_hub(prev)


def _get(url):
    import urllib.request
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read()


# --- gauge history ----------------------------------------------------------

def test_history_rings_bounded_and_windowed():
    h = GaugeHistory(maxlen=8)
    for i in range(20):
        h.record(100.0 + i, {'a.x': float(i)})
    pts = h.window('a.x', 100.0, now=119.0)
    assert len(pts) == 8                       # bounded, newest win
    assert pts[-1] == (119.0, 19.0)
    assert h.window('a.x', 3.0, now=119.0) == [
        (116.0, 16.0), (117.0, 17.0), (118.0, 18.0), (119.0, 19.0)]
    assert h.window('a.x', 0.0) == [(119.0, 19.0)]   # per-sample window
    assert h.window('missing', 5.0) == []
    assert h.latest('a.x') == (119.0, 19.0)
    assert h.has('a.x') and not h.has('a.y')


def test_history_rate_and_quantile_reductions():
    h = GaugeHistory()
    for i in range(11):
        h.record(50.0 + i, {'c.steps': 10.0 * i, 'c.lat': float(i)})
    # slope over the window: 10 units/sec
    assert h.reduce('c.steps', 'rate', 10.0, now=60.0) \
        == pytest.approx(10.0)
    assert h.reduce('c.lat', 'max', 4.0, now=60.0) == 10.0
    assert h.reduce('c.lat', 'min', 4.0, now=60.0) == 6.0
    assert h.reduce('c.lat', 'mean', 4.0, now=60.0) == 8.0
    assert h.reduce('c.lat', 'p50', 4.0, now=60.0) == 8.0
    # a one-point window has no slope
    assert h.reduce('c.steps', 'rate', 0.5, now=60.0) is None
    assert h.reduce('missing', 'mean', 5.0) is None
    with pytest.raises(ValueError):
        h.reduce('c.lat', 'median', 5.0)


def test_sampler_ticks_listeners_and_thread_lifecycle():
    vals = {'s.x': 1.0}
    sampler = GaugeSampler(lambda: dict(vals), period=0.01)
    seen = []
    sampler.add_listener(lambda now, hist: seen.append(now))
    sampler.tick(now=7.0)
    vals['s.x'] = 2.0
    sampler.tick(now=8.0)
    assert [v for _t, v in sampler.history.window('s.x', 10.0,
                                                  now=8.0)] == [1.0, 2.0]
    assert seen == [7.0, 8.0]
    # maybe_tick paces by period
    assert sampler.maybe_tick(now=9.0) is True
    assert sampler.maybe_tick(now=9.001) is False
    assert sampler.maybe_tick(now=9.02) is True
    # the thread form starts/stops clean (leak fixture holds the line)
    sampler.start()
    deadline = time.monotonic() + 5
    while sampler.stats()[0] < 8 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert sampler.close(timeout=5.0)
    assert not any(t.name == 'cxxnet-obs-sampler'
                   for t in threading.enumerate() if t.is_alive())


def test_sampler_broken_source_degrades_not_raises():
    sampler = GaugeSampler(lambda: 1 / 0, period=0.01)
    sampler.tick(now=1.0)
    ticks, errors = sampler.stats()
    assert (ticks, errors) == (0, 1)


def test_hub_gauge_snapshot_spells_like_metrics(hub):
    s = StatSet()
    s.inc('requests', 3)
    s.inc('rows[b8]', 16)
    for v in (1.0, 2.0, 3.0, 4.0):
        s.observe('latency_ms', v)
    hub.register_stats('serve', s)
    snap = hub.gauge_snapshot()
    assert snap['serve.requests'] == 3.0
    assert snap['serve.rows[b8]'] == 16.0
    assert snap['serve.latency_ms.p50'] == 2.5
    assert snap['serve.latency_ms.n'] == 4.0
    assert snap['obs.uptime_s'] > 0


def test_gauge_snapshot_reduces_newest_tail_only(hub):
    """The sampler tick is O(SAMPLE_TAIL) per distribution: quantiles
    reduce the NEWEST tail (recent behavior — what a time-series ring
    wants) while ``.n`` keeps the true retained count, so an uncleared
    100k-sample serving latency list never rides the 20 Hz tick."""
    s = StatSet()
    for v in range(10_000):                 # old regime: 0..9999
        s.observe('lat', float(v))
    for _ in range(hub.SAMPLE_TAIL):        # new regime: constant 1e6
        s.observe('lat', 1e6)
    hub.register_stats('serve', s)
    snap = hub.gauge_snapshot()
    assert snap['serve.lat.p50'] == 1e6     # newest tail only
    assert snap['serve.lat.n'] == 10_000 + hub.SAMPLE_TAIL
    counters, samples = s.tail_view(4)
    assert samples['lat'] == ([1e6] * 4, 10_000 + hub.SAMPLE_TAIL)


# --- spec grammar -----------------------------------------------------------

def test_spec_grammar_parses_ops_window_burn():
    sp = SLOSpec.parse('fresh', 'online.freshness_s.p99<=0.25@60')
    assert (sp.key, sp.op, sp.threshold, sp.window, sp.burn) == \
        ('online.freshness_s.p99', '<=', 0.25, 60.0, 1.0)
    sp = SLOSpec.parse('floor', 'fleet.elastic_steps.max.rate>=2@30:2.5')
    assert (sp.op, sp.threshold, sp.window, sp.burn) == \
        ('>=', 2.0, 30.0, 2.5)
    assert sp.describe() == 'fleet.elastic_steps.max.rate>=2@30:2.5'
    assert SLOSpec.parse('d', 'serve.queue_depth<32@5').op == '<'
    assert SLOSpec.parse('t', 'a.b>1e-3@0.5').threshold == 1e-3
    assert not SLOSpec.parse('k', 'a.b<=5@1').violates(5.0)
    assert SLOSpec.parse('k', 'a.b<=5@1').violates(5.1)


@pytest.mark.parametrize('bad', [
    'nodots<=1@5',          # key must be <set>.<key>
    'a.b!=1@5',             # unknown op
    'a.b<=1',               # window required
    'a.b<=@5',              # threshold required
    'a.b<=1@5:',            # dangling burn
])
def test_spec_grammar_rejects_malformed(bad):
    with pytest.raises(ValueError, match='slo.x'):
        SLOSpec.parse('x', bad)


# --- windowed verdicts ------------------------------------------------------

def _engine(spec_text, log=None):
    hist = GaugeHistory()
    # `is None`, not truthiness: an EMPTY FailureLog is falsy
    eng = SLOEngine(hist,
                    log=log if log is not None else faults.FailureLog())
    eng.add(SLOSpec.parse('obj', spec_text))
    return hist, eng


def test_multi_window_verdict_transitions_no_flap():
    """THE verdict contract: blip -> AT_RISK, sustained -> BREACHED
    (counted once, typed record in the log), recovery -> OK."""
    log = faults.FailureLog()
    hist, eng = _engine('probe.err<=5@12:10', log=log)   # alarm at 100%
    t = 1000.0
    for i in range(12):
        hist.record(t + i, {'probe.err': 1.0})
    assert eng.evaluate(t + 11)['obj']['state'] == OK
    # violation starts: the 1s short window fills with bad samples
    # first (AT_RISK), the 12s long window only after it sustains
    hist.record(t + 12, {'probe.err': 9.0})
    assert eng.evaluate(t + 12)['obj']['state'] == OK      # 50% short
    hist.record(t + 13, {'probe.err': 9.0})
    assert eng.evaluate(t + 13)['obj']['state'] == AT_RISK
    for i in range(14, 25):
        hist.record(t + i, {'probe.err': 9.0})
        eng.evaluate(t + i)
    assert eng.state('obj') == BREACHED
    assert eng.breached() and eng.breaches('obj') == 1
    recs = log.records('SLOBreachError')
    assert len(recs) == 1 and 'obj' in recs[0].detail
    assert isinstance(eng.last_breach, faults.SLOBreachError)
    # ongoing breach: no new count, no log flood
    hist.record(t + 25, {'probe.err': 9.0})
    eng.evaluate(t + 25)
    assert eng.breaches('obj') == 1
    assert len(log.records('SLOBreachError')) == 1
    # recovery drains the windows back to OK
    for i in range(26, 40):
        hist.record(t + i, {'probe.err': 1.0})
        eng.evaluate(t + i)
    assert eng.state('obj') == OK and not eng.breached()
    with pytest.raises(faults.SLOBreachError):
        eng.check_strict()        # strict still reports the run's breach


def test_default_burn_budget_spike_is_at_risk_only():
    """With the default 10% budget a 2-sample spike alarms the short
    window but not the 60-sample long one — AT_RISK, never BREACHED."""
    hist, eng = _engine('probe.err<=5@60')
    t = 500.0
    for i in range(60):
        hist.record(t + i, {'probe.err': 1.0})
    hist.record(t + 60, {'probe.err': 9.0})
    hist.record(t + 61, {'probe.err': 9.0})
    rec = eng.evaluate(t + 61)['obj']
    assert rec['state'] == AT_RISK
    assert rec['ratio_short'] >= 0.1 > rec['ratio_long']
    assert eng.breaches('obj') == 0


def test_rate_reduction_spec_floors_throughput():
    """A `.rate` suffix over a sampled counter reduces each window to
    one slope — the steps/sec-floor shape: a stalled counter breaches,
    a ramping one is OK."""
    log = faults.FailureLog()
    hist, eng = _engine('train.steps.rate>=5@6', log=log)
    t = 100.0
    now = t
    for i in range(61):                       # 10 steps/sec ramp
        now = t + 0.1 * i
        hist.record(now, {'train.steps': float(i)})
    assert eng.evaluate(now)['obj']['state'] == OK
    for i in range(61, 181):                  # full stall: slope -> 0
        now = t + 0.1 * i
        hist.record(now, {'train.steps': 60.0})
        eng.evaluate(now)
    assert eng.state('obj') == BREACHED
    assert log.records('SLOBreachError')


def test_no_data_is_ok_but_flagged_watching_nothing():
    """A spec whose key never matches a sampled gauge (typo, gauge
    never registered) must not read as a reassuring plain OK: state
    stays OK but no_data flags it on /slos and /metrics."""
    hist, eng = _engine('ghost.gauge<=1@5')
    rec = eng.evaluate(123.0)['obj']
    assert rec['state'] == OK and rec['samples_n'] == 0
    assert rec['value'] is None
    assert rec['no_data'] is True
    assert eng.status_view()['obj']['no_data'] is True
    eng._refresh_gauges()
    assert eng.stats.get('no_data[obj]') == 1
    # data arriving clears the flag
    hist.record(124.0, {'ghost.gauge': 0.5})
    assert eng.evaluate(124.0)['obj']['no_data'] is False
    eng._refresh_gauges()
    assert eng.stats.get('no_data[obj]') == 0


def test_cli_rejects_per_sample_spec():
    """@0 specs are engine-API-only (SLOEngine.observe): from the CLI
    nothing would ever feed one — a dead objective reading OK forever —
    so config parse fails fast."""
    from cxxnet_tpu.main import LearnTask
    task = LearnTask()
    task.set_param('slo.ok_spec', 'serve.queue_depth<=32@5')
    with pytest.raises(ValueError, match='window > 0'):
        task.set_param('slo.dead', 'online.freshness_s<=0.5@0')
    with pytest.raises(ValueError, match='cannot parse'):
        task.set_param('slo.bad', 'not-a-spec')


def test_per_sample_spec_counts_every_violation():
    """window=0 = the freshness shape: each violating observe() is its
    own breach, judged the moment it is measured."""
    log = faults.FailureLog()
    eng = SLOEngine(log=log)
    eng.add(SLOSpec.parse('cap', 'probe.v<=1@0'))
    assert eng.observe('cap', 0.5) == OK
    assert eng.observe('cap', 2.0, step=7) == BREACHED
    assert eng.observe('cap', 3.0) == BREACHED
    assert eng.breaches('cap') == 2
    recs = log.records('SLOBreachError')
    assert len(recs) == 2 and recs[0].step == 7
    assert eng.observe('cap', 0.1) == OK       # state follows the sample
    assert not eng.breached()


# --- freshness through the generic engine -----------------------------------

def test_freshness_is_an_engine_consumer_behavior_equal():
    """The rebased tracker: breach judgment IS the generic engine —
    typed FreshnessSLOError from the factory, historical log kind with
    the version as step, per-sample breach counting, strict raise."""
    from cxxnet_tpu.online.freshness import FreshnessTracker
    log = faults.FailureLog()
    tr = FreshnessTracker(slo_s=0.001, log=log)
    assert isinstance(tr.slo, SLOEngine)
    spec = tr.slo.specs()['freshness']
    assert spec.window == 0.0 and spec.kind == 'freshness_slo_breach'
    tr.record_step(20, time.monotonic() - 1.0)
    tr.record_swap(20)
    assert tr.note_served(20) > 0.5
    assert tr.breaches == 1
    err = tr.last_breach
    assert isinstance(err, faults.FreshnessSLOError)
    assert isinstance(err, faults.SLOBreachError)     # the new taxonomy
    assert isinstance(err, faults.ServeError)         # embedder contract
    assert err.step == 20
    recs = log.records('freshness_slo_breach')
    assert len(recs) == 1 and recs[0].step == 20
    assert not log.records('SLOBreachError')          # historical kind
    with pytest.raises(faults.FreshnessSLOError):
        tr.check_strict()
    # verdict history records the judged sample
    view = tr.slo.status_view()['freshness']
    assert view['state'] == BREACHED and view['breaches'] == 1


def test_freshness_breach_kind_does_not_dump_postmortem(hub, tmp_path):
    """freshness_slo_breach stays an eval-line concern: the armed
    recorder must NOT ship a postmortem for it (behavior-equal to the
    pre-engine path), while the generic SLOBreachError kind does."""
    hub.arm_flight_recorder(str(tmp_path / 'flight'))
    log = faults.FailureLog()
    log.record('freshness_slo_breach', 'late swap', step=8)
    assert not os.path.exists(tmp_path / 'flight')
    log.record('SLOBreachError', 'queue depth over budget')
    assert len(os.listdir(tmp_path / 'flight')) == 1


# --- the FaultPlan drill: breach -> typed postmortem (acceptance) -----------

def test_fault_plan_stall_breaches_slo_with_postmortem(hub, tmp_path):
    """Acceptance: a FaultPlan drill (stall_batch) degrades a real
    pipeline gauge, the sampled SLO transitions to BREACHED, and the
    flight recorder ships a postmortem containing the breaching
    window's samples and the verdict history — nobody calls dump()."""
    hub.arm_flight_recorder(str(tmp_path / 'flight'))
    stats = StatSet()
    hub.register_stats('io', stats)
    sampler = GaugeSampler(hub.gauge_snapshot, period=0.05)
    eng = SLOEngine(sampler.history)
    eng.add(SLOSpec.parse('pipeline', 'io.buffer.starved_ms.p99<=50@1:10'))
    eng.register_into(hub)
    sampler.add_listener(eng.on_tick)
    plan = faults.FaultPlan(stall_batch=((2, 0.3),))
    prev = faults.install_plan(plan)
    tb = ThreadBuffer(lambda: iter(range(6)), buffer_size=1,
                      fault_scope='batch')
    tb.stats = stats
    try:
        consumed = list(tb)
        assert consumed == list(range(6))
        assert plan.fired() == ['stall_batch=2:0.3']
        # the drill parked the consumer ~300ms: starved_ms.p99 >> 50
        assert stats.quantile('buffer.starved_ms', 0.99) > 50
        # drive the sampler deterministically through both windows
        t0 = time.monotonic()
        for i in range(16):
            sampler.tick(t0 + 0.1 * i)
    finally:
        faults.install_plan(prev)
        tb.close(5.0)
        eng.close()
    assert eng.state('pipeline') == BREACHED
    dumps = sorted(os.listdir(tmp_path / 'flight'))
    assert dumps and 'SLOBreachError' in dumps[0], dumps
    with open(tmp_path / 'flight' / dumps[0]) as f:
        d = json.load(f)
    assert d['reason'] == 'SLOBreachError'
    view = d['slos']['pipeline']
    assert view['state'] == BREACHED
    assert view['window_samples'], 'breaching window samples missing'
    assert max(v for _t, v in view['window_samples']) > 50
    assert any(h['state'] == BREACHED for h in view['history'])
    assert any(r['kind'] == 'SLOBreachError' for r in d['failure_log'])


# --- hub roster / endpoints -------------------------------------------------

def test_register_into_hub_serves_verdict_rows_and_slos(hub):
    eng = SLOEngine(log=faults.FailureLog())
    eng.add(SLOSpec.parse('cap', 'probe.v<=1@0'))
    eng.register_into(hub)
    try:
        eng.observe('cap', 5.0)
        text = hub.metrics_text()
        assert 'cxxnet_slo_verdict{tag="cap"} 2' in text
        assert 'cxxnet_slo_breaches{tag="cap"} 1' in text
        view = hub.slos_view()
        assert view['cap']['state'] == BREACHED
        assert view['cap']['spec'] == 'probe.v<=1@0'
        # /statusz carries the same view through the status registry
        assert hub.status()['status']['slo']['cap']['breaches'] == 1
    finally:
        eng.close()
    assert hub.slos_view() == {} and hub.slo_engines() == []


def test_healthz_degrades_while_breached_still_200(hub):
    eng = SLOEngine(log=faults.FailureLog())
    eng.add(SLOSpec.parse('cap', 'probe.v<=1@0'))
    eng.register_into(hub)
    srv = ObsServer(hub, port=0)
    try:
        assert _get(f'{srv.url}/healthz') == b'ok\n'
        eng.observe('cap', 9.0)
        assert _get(f'{srv.url}/healthz') == b'degraded\n'   # HTTP 200
        slos = json.loads(_get(f'{srv.url}/slos'))
        assert slos['cap']['state'] == BREACHED
        assert slos['cap']['window_samples']
        eng.observe('cap', 0.5)                              # recovers
        assert _get(f'{srv.url}/healthz') == b'ok\n'
    finally:
        eng.close()
        assert srv.close(timeout=10.0)


def test_wrapper_and_capi_obs_slos(hub):
    from cxxnet_tpu import capi, wrapper
    eng = SLOEngine(log=faults.FailureLog())
    eng.add(SLOSpec.parse('cap', 'probe.v<=1@0'))
    eng.register_into(hub)
    try:
        eng.observe('cap', 9.0)
        net = capi.net_create('cpu', '')
        for payload in (wrapper.Net(dev='cpu').obs_slos(),
                        capi.net_obs_slos(net)):
            view = json.loads(payload)
            assert view['cap']['state'] == BREACHED
    finally:
        eng.close()


# --- per-rank endpoints + fleet units ---------------------------------------

def test_obs_servers_ephemeral_ports_no_collision(hub, tmp_path):
    """The elastic-rank shape: N ObsServers at obs.port=0 in one test
    process bind N distinct ports, announce them into port files, and
    shut down clean (the conftest leak fixture holds the line)."""
    servers = [ObsServer(hub, port=0,
                         port_file=str(tmp_path / f'rank{i}.port'))
               for i in range(3)]
    try:
        ports = [s.port for s in servers]
        assert len(set(ports)) == 3
        for i, s in enumerate(servers):
            announced = int((tmp_path / f'rank{i}.port').read_text())
            assert announced == s.port
            assert _get(f'{s.url}/healthz') == b'ok\n'
    finally:
        for s in servers:
            assert s.close(timeout=10.0)
    alive = {t.name for t in threading.enumerate() if t.is_alive()}
    assert not any(n.startswith('cxxnet-obs-') for n in alive)


def test_merge_metrics_injects_rank_labels():
    texts = {
        0: ('# TYPE cxxnet_x gauge\ncxxnet_x 1\n'
            'cxxnet_serve_rows{tag="b8"} 4\n'),
        1: 'cxxnet_x 2\n',
        2: None,                       # dead rank: rows just drop
    }
    merged = merge_metrics(texts)
    assert 'cxxnet_x{rank="0"} 1' in merged
    assert 'cxxnet_x{rank="1"} 2' in merged
    assert 'cxxnet_serve_rows{rank="0",tag="b8"} 4' in merged
    assert merged.count('# TYPE cxxnet_x gauge') == 1
    assert parse_gauges(texts[0]) == {'x': 1.0}   # labeled rows skipped


def test_fleet_scraper_aggregates_and_survives_rank_death(hub):
    """Two live per-rank hubs scraped into one rank-labeled exposition
    + fleet.* aggregates; killing one rank degrades ranks_alive and
    drops its rows — the scrape itself never fails."""
    hubs = [TelemetryHub(ring_events=32) for _ in range(2)]
    for rank, h in enumerate(hubs):
        s = StatSet()
        s.gauge('steps', 10.0 * (rank + 1))
        h.register_stats('elastic', s)
    servers = [ObsServer(h, port=0) for h in hubs]
    scraper = FleetScraper()
    try:
        for rank, s in enumerate(servers):
            scraper.add_target(rank, s.url)
        src = scraper.source()
        assert src['fleet.ranks_alive'] == 2.0
        assert src['fleet.elastic_steps.min'] == 10.0
        assert src['fleet.elastic_steps.max'] == 20.0
        assert src['fleet.elastic_steps.sum'] == 30.0
        merged = scraper.merged_metrics()
        assert 'cxxnet_elastic_steps{rank="0"} 10' in merged
        assert 'cxxnet_elastic_steps{rank="1"} 20' in merged
        assert 'cxxnet_fleet_ranks_alive 2' in merged
        # rank 1 dies mid-run: the next scrape survives and says so
        servers[1].close(timeout=10.0)
        src = scraper.source()
        assert src['fleet.ranks_alive'] == 1.0
        assert src['fleet.elastic_steps.max'] == 10.0
        merged = scraper.merged_metrics()
        assert 'rank="1"' not in merged
        assert 'cxxnet_fleet_ranks_alive 1' in merged
        assert scraper.alive() == {0: True, 1: False}
        assert scraper.scrape_errors() >= 1
        # the merged endpoint serves through the same scraper
        fsrv = FleetServer(scraper, port=0)
        try:
            text = _get(f'{fsrv.url}/metrics').decode()
            assert 'cxxnet_elastic_steps{rank="0"} 10' in text
            st = json.loads(_get(f'{fsrv.url}/statusz'))
            assert st['ranks']['0']['alive'] is True
            assert st['ranks']['1']['alive'] is False
            assert _get(f'{fsrv.url}/healthz') == b'ok\n'
            assert json.loads(_get(f'{fsrv.url}/slos')) == {}
        finally:
            assert fsrv.close(timeout=10.0)
    finally:
        for s in servers:
            s.close(timeout=10.0)


def test_merge_chrome_traces_one_lane_per_host(tmp_path):
    for rank in (0, 1):
        with open(tmp_path / f'trace_rank{rank}.json', 'w') as f:
            json.dump({'traceEvents': [
                {'name': 'train.dispatch', 'cat': 'train', 'ph': 'X',
                 'ts': 1.0, 'dur': 2.0, 'pid': 4242, 'tid': 1,
                 'args': {}}]}, f)
    out = merge_chrome_traces(
        {0: str(tmp_path / 'trace_rank0.json'),
         1: str(tmp_path / 'trace_rank1.json'),
         2: str(tmp_path / 'trace_rank2.json')},   # never exported
        str(tmp_path / 'merged.json'))
    assert out is not None
    with open(out) as f:
        trace = json.load(f)
    events = trace['traceEvents']
    assert {e['pid'] for e in events} == {0, 1}    # pid = rank = lane
    lanes = {(e['pid'], e['args']['name']) for e in events
             if e.get('ph') == 'M' and e['name'] == 'process_name'}
    assert lanes == {(0, 'host rank 0'), (1, 'host rank 1')}
    assert merge_chrome_traces({0: str(tmp_path / 'nope.json')},
                               str(tmp_path / 'empty.json')) is None


# --- CLI e2e (in-process) ---------------------------------------------------

def test_cli_slo_keys_sampler_lifecycle_and_verdict_summary(
        tmp_path, capsys):
    """slo.* + obs.sample_every through the real CLI: the sampler runs
    for the whole task, the (deliberately impossible) SLO breaches, the
    exit summary prints the typed verdict, a postmortem lands under
    model_dir/flight, and every obs thread is gone afterwards (leak
    fixture).  Exit stays 0 — an SLO is an alarm, not a kill switch."""
    from cxxnet_tpu.main import main as cli_main
    from tests.test_io import write_mnist
    write_mnist(str(tmp_path), n=128, rows=8, cols=8, seed=4)
    conf = tmp_path / 'train.conf'
    conf.write_text(f"""
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 0
iter = end
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 16
dev = cpu
eta = 0.05
metric[label] = error
num_round = 1
model_dir = {tmp_path}/models
obs.sample_every = 0.05
slo.smoke = "obs.uptime_s<=0.0001@0.3:10"
""")
    log_before = len(faults.global_failure_log().records('SLOBreachError'))
    rc = cli_main([str(conf)])
    assert rc == 0
    out = capsys.readouterr().out
    assert 'obs: slo smoke: BREACHED' in out, out
    assert len(faults.global_failure_log().records('SLOBreachError')) \
        > log_before
    flight = tmp_path / 'models' / 'flight'
    assert any('SLOBreachError' in f for f in os.listdir(flight))
    get_hub().disarm()
