"""graftlint: the invariant checkers, checked (``-m lint``).

Two layers, mirroring how the suite earns trust:

* **fixture layer** — every checker must CATCH its seeded violation
  fixture (tests/fixtures/lint/) and stay SILENT on the clean twin, so
  the checkers themselves cannot silently rot;
* **live layer** — every checker runs over the real package and the
  result must be clean or exactly baselined (lint_baseline.json),
  with the shrink-only ratchet pinning the baseline against growth.

The config-key extractor is also the doc-table parser other suites
consume (test_execution_plan.py's demotion-matrix drift test) — its
table/backtick helpers are pinned here.
"""

import json
import os
import subprocess
import sys

import pytest

from cxxnet_tpu.analysis import (config_keys, core, fault_taxonomy,
                                 jit_ledger, lock_discipline,
                                 monotonic_clock, span_hygiene,
                                 tracer_hygiene)
from cxxnet_tpu.analysis.core import (Finding, Repo, apply_suppressions,
                                      diff_against_baseline, load_baseline,
                                      run_all)

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, 'tests', 'fixtures', 'lint')


def fixture(name):
    return core.Module(FIXDIR, name)


def rules_of(findings):
    return [f.rule for f in findings]


# --- lock-discipline: fixtures ---------------------------------------------

def test_lock_unguarded_counter_caught():
    findings = lock_discipline.check_module(fixture('lock_unguarded.py'))
    assert rules_of(findings) == ['lock-discipline']
    assert 'Pump.count' in findings[0].message
    assert 'worker-thread' in findings[0].message


def test_lock_clean_twin_silent():
    assert lock_discipline.check_module(fixture('lock_clean.py')) == []


def test_lock_history_ring_unguarded_caught():
    """The graftwatch shape: a sampler thread rebinding a bounded
    history ring that a public window() walks must be caught when
    unguarded (torn-ring class) and silent when declared + locked."""
    findings = lock_discipline.check_module(
        fixture('history_unguarded.py'))
    assert rules_of(findings) == ['lock-discipline']
    assert 'HistoryPump.ring' in findings[0].message
    assert 'window' in findings[0].message


def test_lock_history_ring_clean_twin_silent():
    assert lock_discipline.check_module(
        fixture('history_clean.py')) == []


def test_lock_declared_guard_violation_caught():
    src = '''\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []            # guarded-by: _lock

    def peek(self):
        return self.items          # read without the lock
'''
    mod = core.parse_snippet(src)
    findings = lock_discipline.check_module(mod)
    assert rules_of(findings) == ['lock-discipline']
    assert 'Box.items' in findings[0].message
    assert 'peek' in findings[0].message


def test_lock_guard_must_name_a_real_lock():
    src = '''\
class Box:
    def __init__(self):
        self.items = []            # guarded-by: _lock
'''
    findings = lock_discipline.check_module(core.parse_snippet(src))
    assert ['lock-discipline'] == rules_of(findings)
    assert 'no lock attribute' in findings[0].message


def test_lock_closure_does_not_inherit_with_block():
    """A closure defined inside `with self._lock:` runs LATER — its
    body must not count as lock-held (deferred-execution bug class)."""
    src = '''\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []            # guarded-by: _lock
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            def later():
                return self.items
            self.cb = later
'''
    findings = lock_discipline.check_module(core.parse_snippet(src))
    assert ['lock-discipline'] == rules_of(findings)
    assert 'later' in findings[0].message


def test_lock_order_inverted_caught():
    findings = lock_discipline.order_findings(
        [fixture('lock_order_inverted.py')])
    assert rules_of(findings) == ['lock-order']
    assert 'Transfer._alock' in findings[0].message
    assert 'Transfer._block' in findings[0].message


def test_lock_order_clean_twin_silent():
    assert lock_discipline.order_findings(
        [fixture('lock_order_clean.py')]) == []


def test_lock_order_cross_module_cycle():
    """The graph is global: each module alone is consistent, together
    they form an ABBA cycle — the cross-subsystem deadlock shape."""
    a = core.parse_snippet('''\
def publish():
    with registry_lock:
        with engine_lock:
            pass
''', 'a.py')
    b = core.parse_snippet('''\
def evict():
    with engine_lock:
        with registry_lock:
            pass
''', 'b.py')
    assert lock_discipline.order_findings([a]) == []
    assert lock_discipline.order_findings([b]) == []
    cyc = lock_discipline.order_findings([a, b])
    assert rules_of(cyc) == ['lock-order']
    assert 'registry_lock' in cyc[0].message
    assert 'engine_lock' in cyc[0].message


# --- tracer-hygiene: fixtures -----------------------------------------------

def test_tracer_violations_caught():
    findings = tracer_hygiene.check_module(fixture('tracer_item.py'))
    msgs = ' | '.join(f.message for f in findings)
    assert '.item()' in msgs                 # sync inside the scan body
    assert 'float()' in msgs                 # sync inside the jitted fn
    assert 'time.time()' in msgs             # trace-time constant
    assert 'print()' in msgs
    assert all(f.rule == 'tracer-hygiene' for f in findings)


def test_tracer_scan_body_attribution():
    """The .item() is reported at the innermost fn (the scan body),
    exactly once — not re-reported for every enclosing traced fn."""
    findings = tracer_hygiene.check_module(fixture('tracer_item.py'))
    items = [f for f in findings if '.item()' in f.message]
    assert len(items) == 1
    assert 'body' in items[0].message


def test_tracer_clean_twin_silent():
    assert tracer_hygiene.check_module(fixture('tracer_clean.py')) == []


def test_tracer_tree_map_is_not_lax_map():
    """`jax.tree.map(lambda ...)` is host code — the lambda must NOT be
    treated as traced (live false positive this checker once had)."""
    src = '''\
import jax
import numpy as np

def place(tree):
    return jax.tree.map(lambda h: jax.device_put(np.asarray(h)), tree)
'''
    assert tracer_hygiene.check_module(core.parse_snippet(src)) == []


def test_tracer_pallas_kernel_sync_caught():
    """Pallas kernel bodies are traced scope: both resolution paths —
    pallas_call(<name>, ...) and the local kernel = functools.partial(fn)
    assignment idiom — must surface their seeded host syncs."""
    findings = tracer_hygiene.check_module(
        fixture('pallas_kernel_sync.py'))
    assert len(findings) == 2
    assert all(f.rule == 'tracer-hygiene' for f in findings)
    msgs = ' | '.join(f.message for f in findings)
    assert '_scale_kernel' in msgs and 'float()' in msgs   # direct name
    assert '_stamp_kernel' in msgs and 'time.monotonic()' in msgs  # partial


def test_tracer_pallas_kernel_clean_twin_silent():
    """...and the clean twin — same kernel shapes, host work on the host
    side (incl. a float() in the UNtraced builder fn) — stays silent."""
    assert tracer_hygiene.check_module(
        fixture('pallas_kernel_clean.py')) == []


def test_tracer_param_indirect_kernel_caught():
    """The closed soundness hole: a kernel handed to a HELPER that
    forwards its parameter into pallas_call position 0 is traced scope —
    positionally and by keyword (through an inline partial)."""
    findings = tracer_hygiene.check_module(
        fixture('pallas_param_indirect_sync.py'))
    assert len(findings) == 2
    assert all(f.rule == 'tracer-hygiene' for f in findings)
    msgs = ' | '.join(f.message for f in findings)
    assert '_sync_kernel' in msgs and 'float()' in msgs       # positional
    assert '_clock_kernel' in msgs and 'time.monotonic()' in msgs  # kw


def test_tracer_param_indirect_clean_twin_silent():
    """...while calling the same helpers with clean kernels — and doing
    host float() work around the call — stays silent: only the argument
    matching the forwarded parameter becomes traced scope."""
    assert tracer_hygiene.check_module(
        fixture('pallas_param_indirect_clean.py')) == []


# --- fault-taxonomy: fixtures ------------------------------------------------

@pytest.fixture(scope='module')
def fault_names():
    return fault_taxonomy.fault_class_names(Repo(REPO))


def test_fault_names_resolved(fault_names):
    assert {'TrainingFault', 'DivergenceError', 'ServeError',
            'DeadlineExceededError', 'FreshnessSLOError',
            'FaultInjected', 'RetryError'} <= fault_names
    assert 'FailureLog' not in fault_names
    assert 'RetryPolicy' not in fault_names


def test_fault_raw_raise_and_swallow_caught(fault_names):
    mod = fixture('faults_raw_raise.py')
    findings = fault_taxonomy.check_module(mod, fault_names)
    msgs = ' | '.join(f.message for f in findings)
    assert 'raise RuntimeError' in msgs
    assert 'broad "except Exception"' in msgs
    assert len(findings) == 2


def test_fault_clean_twin_silent(fault_names):
    mod = fixture('faults_clean.py')
    findings = apply_suppressions(
        fault_taxonomy.check_module(mod, fault_names), mod)
    assert findings == []


def test_fault_tuple_form_broad_except_caught(fault_names):
    """`except (Exception, X):` swallows everything `except Exception:`
    does — the tuple spelling must not evade the rule."""
    src = '''\
def f(x):
    try:
        return x()
    except (Exception, ValueError):
        return None
'''
    findings = fault_taxonomy.check_module(core.parse_snippet(src),
                                           fault_names)
    assert rules_of(findings) == ['fault-taxonomy']


def test_fault_base_exception_stays_out_of_scope(fault_names):
    """`except BaseException` is the package's deliberate propagate-to-
    consumer pattern (thread_buffer/pool) — not flagged."""
    src = '''\
def f(x):
    try:
        return x()
    except BaseException:
        raise
'''
    assert fault_taxonomy.check_module(core.parse_snippet(src),
                                       fault_names) == []


def test_fault_allow_requires_matching_rule(fault_names):
    src = '''\
def f(x):
    try:
        return x()
    except Exception:  # lint: allow(monotonic-clock): wrong rule
        return None
'''
    mod = core.parse_snippet(src)
    findings = apply_suppressions(
        fault_taxonomy.check_module(mod, fault_names), mod)
    assert rules_of(findings) == ['fault-taxonomy']


# --- config-key-drift: fixtures + the shared extractor -----------------------

@pytest.fixture(scope='module')
def fixture_doc_keys():
    with open(os.path.join(FIXDIR, 'config_doc.md')) as f:
        return config_keys.doc_keys(f.read())


def test_config_undocumented_key_caught(fixture_doc_keys):
    findings = config_keys.check_module(
        fixture('config_undocumented.py'), fixture_doc_keys,
        doc_files=('config_doc.md',))
    assert rules_of(findings) == ['config-key-drift']
    assert "'io.mystery'" in findings[0].message


def test_config_clean_twin_silent(fixture_doc_keys):
    assert config_keys.check_module(
        fixture('config_clean.py'), fixture_doc_keys,
        doc_files=('config_doc.md',)) == []


def test_parsed_keys_sees_both_idioms():
    keys = config_keys.parsed_keys(fixture('config_undocumented.py'))
    assert {'num_round', 'model_dir', 'io.mystery', 'data'} == set(keys)


def test_doc_table_rows_and_backtick_key():
    text = ('## Keys\n\n| key | meaning |\n|---|---|\n'
            '| `alpha` | first |\n| `beta = 2` | second (runtime) |\n')
    rows = config_keys.doc_table_rows(text)
    keyed = [(config_keys.backtick_key(r[0]), r[1]) for r in rows
             if config_keys.backtick_key(r[0])]
    assert keyed == [('alpha', 'first'), ('beta', 'second (runtime)')]
    assert config_keys.doc_table_rows(text, after='nowhere') == []


def test_live_extractor_sees_cli_keys():
    repo = Repo(REPO)
    keys = config_keys.parsed_keys(repo.module('cxxnet_tpu/main.py'))
    assert {'task', 'num_round', 'continue', 'steps_per_dispatch',
            'train.supervise', 'serve.mode', 'online.qps', 'data',
            'pred'} <= set(keys)


# --- monotonic-clock: fixtures ----------------------------------------------

def test_clock_wall_deadline_caught():
    findings = monotonic_clock.check_module(fixture('clock_wall.py'))
    assert rules_of(findings) == ['monotonic-clock'] * 2


def test_clock_clean_twin_and_allowed_stamp_silent():
    mod = fixture('clock_clean.py')
    raw = monotonic_clock.check_module(mod)
    assert len(raw) == 1              # the calendar stamp IS detected...
    assert apply_suppressions(raw, mod) == []   # ...and explicitly allowed


def test_clock_from_import_spelling_caught():
    src = 'from time import time\n\ndef f():\n    return time()\n'
    findings = monotonic_clock.check_module(core.parse_snippet(src))
    assert rules_of(findings) == ['monotonic-clock']


def test_clock_aliased_imports_caught():
    """`import time as t` / `from time import time as wall` must not
    evade the rule — an aliased wall-clock deadline is just as wrong."""
    src = ('import time as _t\nfrom time import time as wall\n\n'
           'def f():\n    return wall() + _t.time()\n')
    findings = monotonic_clock.check_module(core.parse_snippet(src))
    assert rules_of(findings) == ['monotonic-clock'] * 2
    # monotonic through an alias stays clean
    src2 = ('import time as _t\n\ndef f():\n    return _t.monotonic()\n')
    assert monotonic_clock.check_module(core.parse_snippet(src2)) == []


# --- span-hygiene: fixtures --------------------------------------------------

def test_span_traced_and_manual_begin_caught():
    """Both halves of the rule fire on the seeded fixture: a span inside
    a lax.scan body (host work in the trace) and a manually-entered
    span (no `with`)."""
    findings = span_hygiene.check_module(fixture('span_traced.py'))
    assert rules_of(findings) == ['span-hygiene', 'span-hygiene']
    msgs = ' | '.join(f.message for f in findings)
    assert 'jitted/scanned scope' in msgs
    assert 'context-manager form' in msgs


def test_span_clean_twin_silent():
    """With-form host-side spans (and the decorator form) pass."""
    assert span_hygiene.check_module(fixture('span_clean.py')) == []


def test_span_rule_keys_on_obs_import():
    """A module with its own unrelated span() helper — and no obs
    import — is out of scope (no misfires on foreign vocabulums)."""
    src = '''\
def span(x):
    return x

def use():
    s = span(3)
    return s
'''
    mod = core.parse_snippet(src, rel='cxxnet_tpu/foreign.py')
    assert not span_hygiene._uses_obs(mod)
    repo_like_findings = (span_hygiene.check_module(mod)
                          if span_hygiene._uses_obs(mod) else [])
    assert repo_like_findings == []


def test_span_obs_package_exempt_from_form_only():
    """The obs package constructs spans (its module-level span() helper
    returns one) — exempt from the with-form check, NOT from the
    traced-scope check."""
    src = '''\
from jax import lax
from cxxnet_tpu.obs.hub import span

def helper():
    return span('ok', 'obs')

def bad(xs):
    def body(c, x):
        with span('bad', 'obs'):
            return c, x
    return lax.scan(body, 0, xs)
'''
    mod = core.parse_snippet(src, rel='cxxnet_tpu/obs/extra.py')
    findings = span_hygiene.check_module(mod)
    assert rules_of(findings) == ['span-hygiene']
    assert 'jitted/scanned scope' in findings[0].message


# --- jit-ledger: fixtures ----------------------------------------------------

def test_jit_ledger_direct_sites_caught():
    """All four spellings fire: plain call, partial(jax.jit, ...)
    decorator factory, an aliased ``from jax import jit``, and the
    bare ``@jax.jit`` decorator (an Attribute, not a Call — the
    spelling this PR removed from trainer.py, so the most natural
    regression)."""
    findings = jit_ledger.check_module(fixture('jit_ledger_caught.py'))
    assert rules_of(findings) == ['jit-ledger'] * 4
    msgs = ' | '.join(f.message for f in findings)
    assert 'ProgramLedger' in msgs
    assert 'functools.partial' in msgs
    assert 'bare decorator' in msgs


def test_jit_ledger_clean_twin_and_allow_silent():
    """The ledger-routed spelling never mentions jax.jit at the site;
    the one trivial direct jit is detected but explicitly allowed."""
    mod = fixture('jit_ledger_clean.py')
    raw = jit_ledger.check_module(mod)
    assert len(raw) == 1                      # the restage helper IS seen...
    assert apply_suppressions(raw, mod) == []  # ...and allowed with a reason


def test_jit_ledger_scoped_to_nnet_and_serve():
    """A direct jit in models/ (the generate cache's home) is out of
    scope — its programs register at the engine call sites."""
    repo = Repo(REPO)
    scoped = {f.path for f in jit_ledger.run(repo)}
    assert all(p.startswith(('cxxnet_tpu/nnet/', 'cxxnet_tpu/serve/'))
               for p in scoped)


# --- live repo: clean or exactly baselined -----------------------------------

def test_live_repo_clean_or_baselined():
    findings = run_all(root=REPO)
    new, stale, matched = diff_against_baseline(findings,
                                                load_baseline())
    assert new == [], '\n'.join(f.format() for f in new)
    assert stale == [], stale
    assert matched == len(findings)


def test_live_lock_order_acyclic():
    assert run_all(root=REPO, rules=['lock-order']) == []


def test_live_tracer_hygiene_clean():
    assert run_all(root=REPO, rules=['tracer-hygiene']) == []


def test_live_monotonic_clean():
    assert run_all(root=REPO, rules=['monotonic-clock']) == []


def test_live_config_keys_documented():
    assert run_all(root=REPO, rules=['config-key-drift']) == []


def test_live_span_hygiene_clean():
    assert run_all(root=REPO, rules=['span-hygiene']) == []


def test_live_jit_ledger_clean():
    assert run_all(root=REPO, rules=['jit-ledger']) == []


def test_live_threaded_classes_declare_guards():
    """The annotation convention is actually deployed: the flagship
    threaded classes each declare at least one guarded attribute."""
    import ast as _ast
    repo = Repo(REPO)
    expect = {
        'cxxnet_tpu/utils/thread_buffer.py': 'ThreadBuffer',
        'cxxnet_tpu/serve/batcher.py': 'DynamicBatcher',
        'cxxnet_tpu/serve/decode.py': 'DecodeEngine',
        'cxxnet_tpu/serve/registry.py': 'ModelRegistry',
        'cxxnet_tpu/online/pipeline.py': 'OnlinePipeline',
        'cxxnet_tpu/runtime/async_ckpt.py': 'AsyncCheckpointer',
    }
    for rel, cls in expect.items():
        mod = repo.module(rel)
        node = next(n for n in _ast.walk(mod.tree)
                    if isinstance(n, _ast.ClassDef) and n.name == cls)
        info = lock_discipline._ClassInfo(mod, node)
        assert info.guarded, f'{cls} declares no # guarded-by attributes'
        assert info.spawns, f'{cls} expected to spawn worker threads'


# --- baseline: the shrink-only ratchet ---------------------------------------

# Lower this cap when you fix a baselined finding; NEVER raise it.  A
# new finding belongs in the code (fixed) or at its site (# lint:
# allow(rule): reason), not in the baseline.
MAX_BASELINE_ENTRIES = 7


def test_baseline_never_grows():
    entries = load_baseline()
    assert len(entries) <= MAX_BASELINE_ENTRIES, (
        f'lint_baseline.json grew to {len(entries)} entries '
        f'(cap {MAX_BASELINE_ENTRIES}) — the baseline is shrink-only')
    for e in entries:
        assert e['reason'].strip(), e


def test_baseline_policy_field():
    with open(core.baseline_path(REPO)) as f:
        data = json.load(f)
    assert data.get('policy') == 'shrink-only'


def test_stale_baseline_entry_fails():
    entries = load_baseline() + [{
        'rule': 'monotonic-clock', 'path': 'cxxnet_tpu/ghost.py',
        'message': 'long gone', 'reason': 'stale on purpose'}]
    findings = run_all(root=REPO)
    _new, stale, _m = diff_against_baseline(findings, entries)
    assert [e['path'] for e in stale] == ['cxxnet_tpu/ghost.py']


def test_baseline_matching_is_line_independent():
    f = Finding('r', 'p.py', 999, 'msg')
    new, stale, matched = diff_against_baseline(
        [f], [{'rule': 'r', 'path': 'p.py', 'message': 'msg',
               'reason': 'x'}])
    assert (new, stale, matched) == ([], [], 1)


def test_baseline_multiset_matching():
    """Two identical findings need two baseline entries."""
    f = Finding('r', 'p.py', 1, 'msg')
    e = {'rule': 'r', 'path': 'p.py', 'message': 'msg', 'reason': 'x'}
    new, _s, matched = diff_against_baseline([f, f], [e])
    assert matched == 1 and len(new) == 1


# --- tools/lint.py CLI --------------------------------------------------------

LINT = os.path.join(REPO, 'tools', 'lint.py')


def _lint(*args, cwd=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    return subprocess.run([sys.executable, LINT, *args],
                          capture_output=True, text=True, env=env,
                          cwd=cwd or REPO, timeout=300)


def _seed_violation_tree(tmp_path):
    pkg = tmp_path / 'cxxnet_tpu'
    pkg.mkdir()
    (pkg / '__init__.py').write_text('')
    (pkg / 'bad.py').write_text(
        'import time\n\n\ndef deadline(t):\n    return time.time() + t\n')
    return tmp_path


def test_cli_exit0_on_repo():
    r = _lint()
    assert r.returncode == 0, r.stdout + r.stderr
    assert '0 new' in r.stderr


def test_cli_exit1_on_new_finding(tmp_path):
    root = _seed_violation_tree(tmp_path)
    r = _lint(str(root))
    assert r.returncode == 1
    assert 'monotonic-clock' in r.stdout


def test_cli_exit1_on_stale_baseline_and_update_shrinks(tmp_path):
    root = _seed_violation_tree(tmp_path)
    (root / 'cxxnet_tpu' / 'bad.py').write_text('X = 1\n')
    bl = root / 'lint_baseline.json'
    bl.write_text(json.dumps({'policy': 'shrink-only', 'entries': [{
        'rule': 'monotonic-clock', 'path': 'cxxnet_tpu/bad.py',
        'message': 'gone', 'reason': 'stale'}]}))
    r = _lint(str(root))
    assert r.returncode == 1
    assert 'stale baseline entry' in r.stdout
    # --update-baseline drops the stale entry (shrink) and exits clean
    r2 = _lint(str(root), '--update-baseline')
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert json.loads(bl.read_text())['entries'] == []
    # but it NEVER adds: a live finding still fails after update
    (root / 'cxxnet_tpu' / 'bad.py').write_text(
        'import time\n\n\ndef f():\n    return time.time()\n')
    r3 = _lint(str(root), '--update-baseline')
    assert r3.returncode == 1
    assert json.loads(bl.read_text())['entries'] == []


def test_cli_update_baseline_keeps_matched_duplicate(tmp_path):
    """Duplicate identical entries are legitimate (multiset matching):
    when one of two copies goes stale, --update-baseline removes ONE
    occurrence, keeping the copy that still matches a live finding."""
    root = _seed_violation_tree(tmp_path)
    entry = {'rule': 'monotonic-clock', 'path': 'cxxnet_tpu/bad.py',
             'message': 'time.time() is wall-clock — durations and '
                        'deadlines must use time.monotonic() (allow '
                        'with a reason for genuine calendar timestamps)',
             'reason': 'dup'}
    bl = root / 'lint_baseline.json'
    bl.write_text(json.dumps({'policy': 'shrink-only',
                              'entries': [entry, entry]}))
    r = _lint(str(root), '--update-baseline')   # 1 live, 1 stale
    assert r.returncode == 0, r.stdout + r.stderr
    assert len(json.loads(bl.read_text())['entries']) == 1
    assert _lint(str(root)).returncode == 0     # still exactly baselined


def test_cli_exit2_on_unreadable_baseline(tmp_path):
    root = tmp_path
    (root / 'cxxnet_tpu').mkdir()
    (root / 'cxxnet_tpu' / '__init__.py').write_text('')
    (root / 'lint_baseline.json').write_text('{not json')
    r = _lint(str(root))
    assert r.returncode == 2
    assert 'internal error' in r.stderr


def test_cli_rule_filter_and_listing():
    r = _lint('--list-rules')
    assert r.returncode == 0
    assert set(r.stdout.split()) == set(core.ALL_RULES)
    r = _lint('--rule', 'no-such-rule')
    assert r.returncode == 2
