"""Trainer semantics: LR schedules, multi-label graphs, extract, rec@n."""

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.updater.updaters import UpdaterHyper
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.utils.metric import create_metric


def _hyper(**params):
    h = UpdaterHyper()
    for k, v in params.items():
        h.set_param(k, str(v))
    return h


class TestSchedules:
    """Closed-form checks of ``ScheduleEpoch`` (reference param.h:76-94)."""

    def test_expdecay(self):
        h = _hyper(eta=0.1, **{'lr:schedule': 'expdecay', 'lr:gamma': 0.5,
                               'lr:step': 100})
        lr, _ = h.schedule(200)
        assert np.isclose(float(lr), 0.1 * 0.5 ** 2.0)
        lr, _ = h.schedule(50)       # fractional exponent (continuous decay)
        assert np.isclose(float(lr), 0.1 * 0.5 ** 0.5)

    def test_polydecay(self):
        h = _hyper(eta=0.1, **{'lr:schedule': 'polydecay', 'lr:gamma': 2.0,
                               'lr:alpha': 0.5, 'lr:step': 10})
        lr, _ = h.schedule(35)       # floor(35/10)=3 -> (1+3*2)^-0.5
        assert np.isclose(float(lr), 0.1 * (1 + 3 * 2.0) ** -0.5)

    def test_factor_with_minimum(self):
        h = _hyper(eta=0.1, **{'lr:schedule': 'factor', 'lr:factor': 0.1,
                               'lr:step': 10, 'lr:minimum_lr': 5e-4})
        assert np.isclose(float(h.schedule(0)[0]), 0.1)
        assert np.isclose(float(h.schedule(25)[0]), 0.1 * 0.01)
        assert np.isclose(float(h.schedule(99)[0]), 5e-4)   # clamped

    def test_tag_scoped_override(self):
        from cxxnet_tpu.updater.updaters import create_updater_hyper
        defcfg = [('eta', '0.1'), ('wd', '0.001'), ('bias:wd', '0.0')]
        wmat = create_updater_hyper('sgd', 'wmat', defcfg, [])
        bias = create_updater_hyper('sgd', 'bias', defcfg, [])
        assert wmat.wd == pytest.approx(0.001)
        assert bias.wd == pytest.approx(0.0)


MULTILABEL_CONF = """
netconfig = start
layer[0->1] = fullc:fc1
  nhidden = 16
layer[1->2] = sigmoid
layer[2->cls_out] = fullc:cls
  nhidden = 4
layer[cls_out->cls_out] = softmax
layer[2->reg_out] = fullc:reg
  nhidden = 2
layer[reg_out->reg_out] = l2_loss
  target = extra
netconfig = end
input_shape = 1,1,8
batch_size = 16
input_flat = 1
dev = cpu
eta = 0.1
momentum = 0.9
label_vec[0,1) = label
label_vec[1,3) = extra
metric[label,cls_out] = error
metric[extra,reg_out] = rmse
"""


def _multilabel_batch(rng, n=16):
    x = rng.rand(n, 1, 1, 8).astype(np.float32)
    cls = rng.randint(0, 4, (n, 1)).astype(np.float32)
    reg = (x.reshape(n, 8)[:, :2] * 2.0).astype(np.float32)
    return DataBatch(x, np.concatenate([cls, reg], axis=1))


def test_multilabel_two_heads_train():
    """label_vec splits the label matrix into named fields consumed by
    different loss heads (softmax on 'label', l2 on 'extra'); metrics are
    per-field (``nnet_impl:271-285``, ``metric.h:175-236``)."""
    rng = np.random.RandomState(0)
    tr = NetTrainer(parse_config_string(MULTILABEL_CONF))
    tr.init_model()
    batches = [_multilabel_batch(rng) for _ in range(20)]
    first = None
    for r in range(8):
        tr.start_round(r)
        for b in batches:
            tr.update(b)
        res = tr.evaluate(iter(batches[:5]), 'v')
        rmse = float(res.split('v-rmse[extra]:')[-1])
        err = float(res.split('v-error:')[-1].split('\t')[0])
        if first is None:
            first = (err, rmse)
    assert rmse < first[1], 'regression head did not improve'
    assert err <= first[0], 'classification head did not improve'


def test_extract_topk_and_named_node():
    rng = np.random.RandomState(0)
    tr = NetTrainer(parse_config_string(MULTILABEL_CONF))
    tr.init_model()
    b = _multilabel_batch(rng)
    feat = tr.extract_feature(b, 'top[-1]')      # final node (reg head)
    assert feat.shape[-1] == 2
    named = tr.extract_feature(b, 'cls_out')      # named node
    assert named.shape[-1] == 4
    hidden = tr.extract_feature(b, '2')           # node named by index
    assert hidden.reshape(16, -1).shape == (16, 16)


TAIL_CONF = """
netconfig = start
layer[0->1] = fullc:fc1
  nhidden = 8
layer[1->2] = sigmoid
layer[2->3] = fullc:cls
  nhidden = 4
layer[3->3] = softmax
netconfig = end
input_shape = 1,1,6
batch_size = 100
input_flat = 1
dev = cpu
eta = 0.1
metric = error
"""


def _padded_batches(x, y, bs, pad_fill):
    """Split (n, ...) arrays into full batches; pad the short tail with
    ``pad_fill`` rows and set num_batch_padd — the shape the batch adapter
    emits for round_batch=0."""
    n = x.shape[0]
    out = []
    for s in range(0, n, bs):
        xb, yb = x[s:s + bs], y[s:s + bs]
        npadd = bs - xb.shape[0]
        if npadd:
            xb = np.concatenate([xb, np.full((npadd,) + x.shape[1:],
                                             pad_fill, x.dtype)])
            yb = np.concatenate([yb, np.full((npadd, y.shape[1]),
                                             pad_fill, y.dtype)])
        out.append(DataBatch(xb, yb, num_batch_padd=npadd,
                             pad_synthetic=bool(npadd)))
    return out


def test_tail_batch_trains_and_evals_all_instances():
    """A 250-instance dataset at batch 100 trains/evals all 250 — the pad
    rows of the short tail batch (num_batch_padd=50) are masked out of
    gradients and metrics (reference: iter_batch_proc-inl.hpp:101-103 emits
    the tail; nnet_impl-inl.hpp:239 excludes pads from eval)."""
    rng = np.random.RandomState(3)
    x = rng.rand(250, 1, 1, 6).astype(np.float32)
    y = rng.randint(0, 4, (250, 1)).astype(np.float32)

    # two trainers, identical seed, fed the same real rows but tail pads
    # filled with wildly different garbage: masked pads => identical params
    results = []
    for pad_fill in (0.0, 1e6):
        tr = NetTrainer(parse_config_string(TAIL_CONF))
        tr.init_model()
        tr.start_round(0)
        for b in _padded_batches(x, y, 100, pad_fill):
            tr.update(b)
        import jax
        results.append(jax.device_get(tr.params))
    for (ka, va), (kb, vb) in zip(sorted(results[0].items()),
                                  sorted(results[1].items())):
        for f in va:
            np.testing.assert_array_equal(va[f], vb[f]), (ka, f)
    assert all(np.all(np.isfinite(v[f])) for v in results[1].values()
               for f in v), 'garbage pad rows leaked into gradients'

    # eval counts exactly 250 instances, pads excluded
    tr = NetTrainer(parse_config_string(TAIL_CONF))
    tr.init_model()
    tr.evaluate(iter(_padded_batches(x, y, 100, 1e6)), 'v')
    assert tr.metric.evals[0].cnt_inst == 250


def test_train_metric_counts_tail_instances():
    """eval_train metrics over an epoch with a padded tail count every real
    instance once (250, not 300 or 200)."""
    rng = np.random.RandomState(4)
    x = rng.rand(250, 1, 1, 6).astype(np.float32)
    y = rng.randint(0, 4, (250, 1)).astype(np.float32)
    tr = NetTrainer(parse_config_string(TAIL_CONF))
    tr.init_model()
    tr.start_round(0)
    for b in _padded_batches(x, y, 100, 0.0):
        tr.update(b)
    tr.flush_train_metrics()        # the last step's deferred readback
    assert tr.train_metric.evals[0].cnt_inst == 250


def test_rec_at_n():
    m = create_metric('rec@2')
    pred = np.array([[0.1, 0.5, 0.4], [0.9, 0.05, 0.6]])
    label = np.array([[2.0], [1.0]])      # top2 = {1,2} hit; {0,2} miss
    m.add_eval(pred, label)
    assert m.get() == pytest.approx(0.5)
    with pytest.raises(ValueError):
        bad = create_metric('rec@5')
        bad.add_eval(pred, label)


@pytest.mark.parametrize('update_period', [1, 2])
def test_lookahead_staging_equals_plain_update(update_period):
    """The CLI train loop's one-batch lookahead (stage_batch for i+1
    enqueued before update_staged for i) must produce bitwise-identical
    training to plain per-batch update() — staging must not disturb rng
    streams, counters, masks, gradient accumulation (update_period>1),
    or deferred train metrics."""
    batches = [_multilabel_batch(np.random.RandomState(100 + i))
               for i in range(5)]

    def final_params(drive):
        tr = NetTrainer(parse_config_string(
            MULTILABEL_CONF + f'seed = 7\nupdate_period = {update_period}\n'))
        tr.init_model()
        drive(tr)
        tr.flush_train_metrics()
        return tr

    def plain(tr):
        for b in batches:
            tr.update(b)

    def lookahead(tr):
        pending = None
        for b in batches:
            staged = tr.stage_batch(b)
            if pending is not None:
                tr.update_staged(pending)
            pending = staged
        tr.update_staged(pending)

    t1, t2 = final_params(plain), final_params(lookahead)
    assert t1.sample_counter == t2.sample_counter
    assert t1.epoch_counter == t2.epoch_counter
    # 5 batches at update_period=2: the tail accumulation lives only in
    # grad_acc — compare it too, or a staging bug in a non-applying step
    # would be invisible
    for k, fields in t1.grad_acc.items():
        for f, v in fields.items():
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(t2.grad_acc[k][f]),
                                          err_msg=f'grad_acc {k}/{f}')
    for k, fields in t1.params.items():
        for f, v in fields.items():
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(t2.params[k][f]),
                                          err_msg=f'{k}/{f}')
    assert t1.train_metric.print('t') == t2.train_metric.print('t')


def test_momentum_saturation_schedule():
    """Momentum saturation (updater/param.h:76-94): with the schedule on,
    the effective momentum is min(momentum + ramp(e) + base_momentum,
    final_momentum) — the reference's quirky additive formula, preserved,
    with the unconditional final_momentum cap (param.h:88)."""
    from cxxnet_tpu.updater.updaters import UpdaterHyper
    h = UpdaterHyper(tag='wmat')
    for k, v in (('momentum', '0.0'), ('momentum_schedule', '1'),
                 ('base_momentum', '0.5'), ('final_momentum', '0.9'),
                 ('saturation_epoch', '100')):
        h.set_param(k, v)
    import numpy as _np
    for epoch, want in ((0, 0.5), (50, 0.7), (200, 0.9)):
        _lr, mom = h.schedule(epoch)
        assert _np.asarray(mom) == pytest.approx(want, abs=1e-6)
    # schedule off: static momentum
    h2 = UpdaterHyper(tag='wmat')
    h2.set_param('momentum', '0.8')
    _lr, mom = h2.schedule(123)
    assert _np.asarray(mom) == pytest.approx(0.8)


def test_clip_gradient_clips_and_zeroes_nan():
    """clip_gradient both clips to [-c, c] and zeroes NaN gradients in
    one functor (sgd_updater-inl.hpp:15-22)."""
    import jax.numpy as _jnp
    import numpy as _np
    from cxxnet_tpu.updater.updaters import UpdaterHyper, _sgd_leaf
    h = UpdaterHyper(tag='wmat')
    h.set_param('clip_gradient', '1.0')
    h.set_param('wd', '0')
    g = _jnp.asarray([10.0, _np.nan, -5.0, 0.5])
    w = _jnp.zeros(4)
    m = _jnp.zeros(4)
    w_new, _m_new = _sgd_leaf(w, g, m, lr=1.0, mom=0.0, h=h)
    _np.testing.assert_allclose(_np.asarray(w_new),
                                [-1.0, 0.0, 1.0, -0.5], atol=1e-7)
    # clip_gradient = 0 (default): NaNs pass through untouched
    h0 = UpdaterHyper(tag='wmat')
    h0.set_param('wd', '0')
    w_raw, _ = _sgd_leaf(w, g, m, lr=1.0, mom=0.0, h=h0)
    assert _np.isnan(_np.asarray(w_raw)[1])


def test_nag_updater_matches_reference_math():
    """NAG (nag_updater-inl.hpp:65-72): m' = mom*m - lr*(g + wd*w);
    w' = w + (1+mom)*m' - mom*m."""
    import jax.numpy as _jnp
    import numpy as _np
    from cxxnet_tpu.updater.updaters import UpdaterHyper, _nag_leaf
    h = UpdaterHyper(tag='wmat')
    h.set_param('wd', '0.01')
    w, g, m, lr, mom = 1.0, 0.5, 0.2, 0.1, 0.9
    w2, m2 = _nag_leaf(_jnp.float32(w), _jnp.float32(g), _jnp.float32(m),
                       lr, mom, h)
    m_ref = mom * m - lr * (g + 0.01 * w)
    w_ref = w + (1 + mom) * m_ref - mom * m
    assert _np.asarray(m2) == pytest.approx(m_ref, rel=1e-6)
    assert _np.asarray(w2) == pytest.approx(w_ref, rel=1e-6)


def test_adam_updater_matches_reference_math():
    """Adam (adam_updater-inl.hpp:73-82): decay1/decay2 are (1-beta)
    rates, lr_t = base_lr*sqrt(fix2)/fix1 with fix_i = 1-(1-decay_i)^(e+1),
    and the reference's wd sign quirk (grad -= wd*w) is kept verbatim."""
    import jax.numpy as _jnp
    import numpy as _np
    from cxxnet_tpu.updater.updaters import UpdaterHyper, _adam_leaf
    h = UpdaterHyper(tag='wmat')
    h.set_param('eta', '0.002')
    h.set_param('wd', '0.05')
    # config keys are beta1/beta2, which (reference quirk) directly SET
    # the decay rates 1-beta (adam_updater-inl.hpp:56-57) — non-default
    # values prove the keys land
    h.set_param('beta1', '0.2')
    h.set_param('beta2', '0.005')
    w, g, m1, m2v, epoch = 0.7, 0.3, 0.02, 0.004, 4
    w2, m1n, m2n = _adam_leaf(_jnp.float32(w), _jnp.float32(g),
                              _jnp.float32(m1), _jnp.float32(m2v), epoch, h)
    g_eff = g - 0.05 * w                      # the reference sign quirk
    fix1 = 1.0 - (1.0 - 0.2) ** (epoch + 1)
    fix2 = 1.0 - (1.0 - 0.005) ** (epoch + 1)
    lr_t = 0.002 * _np.sqrt(fix2) / fix1
    m1_ref = m1 + 0.2 * (g_eff - m1)
    m2_ref = m2v + 0.005 * (g_eff * g_eff - m2v)
    w_ref = w - lr_t * (m1_ref / (_np.sqrt(m2_ref) + 1e-8))
    assert _np.asarray(m1n) == pytest.approx(m1_ref, rel=1e-6)
    assert _np.asarray(m2n) == pytest.approx(m2_ref, rel=1e-6)
    assert _np.asarray(w2) == pytest.approx(w_ref, rel=1e-6)


def test_lr_constant_and_start_epoch_hold():
    """The two schedule behaviors TestSchedules doesn't pin: the constant
    schedule, and lr:start_epoch holding the base LR until the start
    epoch is reached (updater/param.h:89-92)."""
    import numpy as _np
    lr, _ = _hyper(eta=0.1).schedule(250)
    assert _np.asarray(lr) == pytest.approx(0.1)
    h = _hyper(eta=0.1, **{'lr:schedule': 'expdecay', 'lr:gamma': 0.5,
                           'lr:step': 100, 'lr:start_epoch': 500})
    lr, _ = h.schedule(250)
    assert _np.asarray(lr) == pytest.approx(0.1)    # held at base before
