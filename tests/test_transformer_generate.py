"""KV-cached decode (``models.transformer.generate``).

The oracle is :func:`reference_loss`'s forward math on the FULL
sequence: greedy decode must be self-consistent with it — every
generated token equals the argmax of the full-forward logits at its
position.  A wrong cache (stale K/V, off-by-one mask, bad position
write) breaks this at the first decoded step.
"""

import jax
import jax.numpy as jnp
import numpy as np

from cxxnet_tpu.models import transformer as T


def _oracle_nodrop_moe(y2, p):
    """Independent no-drop switch route: python loop over experts, numpy
    selection — shares NO code path with _nodrop_moe_ffn.  Math: top-1
    expert by router softmax, output scaled by that probability (the
    switch_gate combine = dispatch * gate_prob contract, minus the
    capacity bound generate() documents away)."""
    y2 = np.asarray(y2, np.float32)
    probs = np.asarray(jax.nn.softmax(
        jnp.asarray(y2) @ p['gate'].astype(jnp.float32), axis=-1))
    ex = probs.argmax(-1)
    outs = []
    for e in range(p['w1'].shape[0]):
        w1 = np.asarray(p['w1'][e], np.float32)
        w2 = np.asarray(p['w2'][e], np.float32)
        outs.append(np.maximum(y2 @ w1, 0.0) @ w2)
    outs = np.stack(outs)                                 # (E, n, d)
    sel = outs[ex, np.arange(len(ex))]                    # (n, d)
    return jnp.asarray(sel * probs[np.arange(len(ex)), ex][:, None])


def _full_logits(params, tokens, cfg):
    """Forward logits for every position — the block math re-derived
    independently (duplicated here deliberately: the test oracle must
    not share code with the implementation under test)."""
    import math
    h = jnp.take(params['embed'], tokens, axis=0)
    for i in range(cfg.num_stages):
        p = jax.tree.map(lambda a, i=i: a[i], params['stages'])
        mb, s, d = h.shape
        hd = d // cfg.num_heads
        y = T._layer_norm(h, p['ln1_scale'], p['ln1_bias'])
        q = (y @ p['wq']).reshape(mb, s, cfg.num_heads, hd)
        k = (y @ p['wk']).reshape(mb, s, cfg.num_heads, hd)
        v = (y @ p['wv']).reshape(mb, s, cfg.num_heads, hd)
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        sc = jnp.einsum('bqhd,bkhd->bhqk', q, k) / math.sqrt(hd)
        sc = jnp.where(mask, sc, -jnp.inf)
        attn = jnp.einsum('bhqk,bkhd->bqhd',
                          jax.nn.softmax(sc.astype(jnp.float32),
                                         axis=-1).astype(k.dtype), v)
        h = h + attn.reshape(mb, s, d) @ p['wo']
        y2 = T._layer_norm(h, p['ln2_scale'], p['ln2_bias'])
        if cfg.num_experts:
            ff = _oracle_nodrop_moe(y2.reshape(mb * s, d), p)
            h = h + ff.reshape(mb, s, d).astype(h.dtype)
        else:
            h = h + jax.nn.relu(y2 @ p['w1']) @ p['w2']
    return (h @ params['head']).astype(jnp.float32)


def _setup(num_experts=0):
    cfg = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                              d_ff=48, num_stages=3, seq_len=32,
                              num_experts=num_experts, attn='local')
    params = T.init_params(np.random.RandomState(0), cfg)
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 64, (2, 5)).astype(np.int32)
    return cfg, params, prompt


class TestGreedyDecode:
    def test_greedy_is_self_consistent_with_full_forward(self):
        cfg, params, prompt = _setup()
        out = np.asarray(T.generate(params, prompt, 8, cfg))
        assert out.shape == (2, 8)
        full = jnp.concatenate([jnp.asarray(prompt), jnp.asarray(out)], 1)
        logits = np.asarray(_full_logits(params, full, cfg))
        # token at position s0+j must be the argmax of position s0+j-1
        s0 = prompt.shape[1]
        for j in range(8):
            np.testing.assert_array_equal(
                out[:, j], logits[:, s0 + j - 1].argmax(-1),
                err_msg=f'decode step {j} diverged from full forward')

    def test_moe_greedy_self_consistent(self):
        cfg, params, prompt = _setup(num_experts=4)
        out = np.asarray(T.generate(params, prompt, 6, cfg))
        full = jnp.concatenate([jnp.asarray(prompt), jnp.asarray(out)], 1)
        logits = np.asarray(_full_logits(params, full, cfg))
        s0 = prompt.shape[1]
        for j in range(6):
            np.testing.assert_array_equal(
                out[:, j], logits[:, s0 + j - 1].argmax(-1))

    def test_deterministic(self):
        cfg, params, prompt = _setup()
        a = np.asarray(T.generate(params, prompt, 5, cfg))
        b = np.asarray(T.generate(params, prompt, 5, cfg))
        np.testing.assert_array_equal(a, b)


class TestEosStop:
    def test_post_eos_positions_are_eos(self):
        cfg, params, prompt = _setup()
        base = np.asarray(T.generate(params, prompt, 8, cfg))
        # choose row 0's 3rd token as the "eos": the rerun must emit the
        # same tokens up to and including its first occurrence per row,
        # then eos forever after
        eos = int(base[0, 2])
        out = np.asarray(T.generate(params, prompt, 8, cfg, eos_id=eos))
        for r in range(out.shape[0]):
            hits = np.nonzero(base[r] == eos)[0]
            cut = hits[0] if len(hits) else 8
            np.testing.assert_array_equal(out[r, :cut + 1],
                                          base[r, :cut + 1])
            assert (out[r, cut:] == eos).all()

    def test_no_eos_matches_plain(self):
        cfg, params, prompt = _setup()
        base = np.asarray(T.generate(params, prompt, 6, cfg))
        # an eos that never fires changes nothing
        out = np.asarray(T.generate(params, prompt, 6, cfg,
                                    eos_id=cfg.vocab_size - 1
                                    if (base != cfg.vocab_size - 1).all()
                                    else None))
        np.testing.assert_array_equal(out, base)


class TestSampling:
    def test_sampling_needs_rng(self):
        cfg, params, prompt = _setup()
        import pytest
        with pytest.raises(ValueError, match='rng'):
            T.generate(params, prompt, 3, cfg, temperature=1.0)

    def test_sampling_shape_and_seed_stability(self):
        cfg, params, prompt = _setup()
        k = jax.random.PRNGKey(7)
        a = np.asarray(T.generate(params, prompt, 6, cfg,
                                  temperature=1.0, rng=k))
        b = np.asarray(T.generate(params, prompt, 6, cfg,
                                  temperature=1.0, rng=k))
        c = np.asarray(T.generate(params, prompt, 6, cfg,
                                  temperature=1.0,
                                  rng=jax.random.PRNGKey(8)))
        assert a.shape == (2, 6)
        np.testing.assert_array_equal(a, b)
        assert (a != c).any(), 'different seeds should diverge somewhere'

    def test_low_temperature_approaches_greedy(self):
        cfg, params, prompt = _setup()
        greedy = np.asarray(T.generate(params, prompt, 5, cfg))
        cold = np.asarray(T.generate(params, prompt, 5, cfg,
                                     temperature=1e-4,
                                     rng=jax.random.PRNGKey(0)))
        np.testing.assert_array_equal(cold, greedy)
