"""Pipeline / expert / composed-parallelism tests on the 8-device CPU mesh.

Every distributed program is validated against a single-device oracle:
same math, no mesh.  The composed TransformerLM step checks both the
forward loss and the parameter update (i.e. the gradients, including the
replica-tying psums) to oracle SGD.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from cxxnet_tpu.models import transformer as tfm
from cxxnet_tpu.parallel.moe import moe_ffn_local, moe_ffn_reference
from cxxnet_tpu.parallel.pipeline import (pipeline_stage_loop,
                                          split_microbatches)


def _devices(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f'need {n} devices, have {len(devs)}')
    return devs[:n]


# --- pipeline -------------------------------------------------------------

def test_pipeline_matches_sequential():
    S, M, mb, d = 4, 8, 2, 16
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(S, d, d).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(S, d).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(M * mb, d).astype(np.float32))

    def stage(p, h):
        return jnp.tanh(h @ p['w'] + p['b'])

    mesh = Mesh(np.asarray(_devices(S)), ('pipe',))
    fn = shard_map(
        functools.partial(pipeline_stage_loop, stage, axis_name='pipe',
                          num_stages=S),
        mesh=mesh,
        in_specs=({'w': P('pipe'), 'b': P('pipe')}, P()),
        out_specs=P(), check_vma=False)
    got = fn({'w': ws, 'b': bs}, split_microbatches(x, M))
    got = got.reshape(M * mb, d)

    ref = x
    for i in range(S):
        ref = jnp.tanh(ref @ ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_flow():
    S, M, mb, d = 2, 4, 2, 8
    rng = np.random.RandomState(1)
    ws = jnp.asarray(rng.randn(S, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(M * mb, d).astype(np.float32))
    mesh = Mesh(np.asarray(_devices(S)), ('pipe',))

    def stage(p, h):
        return jnp.tanh(h @ p)

    def loss_local(ws_local, xs):
        out = pipeline_stage_loop(stage, ws_local, xs,
                                  axis_name='pipe', num_stages=S)
        return (out ** 2).mean()

    def body(ws_in, xs):
        return jax.grad(lambda w: loss_local(w, xs))(ws_in)

    fn = shard_map(body, mesh=mesh, in_specs=(P('pipe'), P()),
                   out_specs=P('pipe'), check_vma=False)
    g = fn(ws, split_microbatches(x, M))

    def ref_loss(ws):
        h = x
        for i in range(S):
            h = jnp.tanh(h @ ws[i])
        return (h ** 2).mean()

    # each pipe rank's autodiff sums both ranks' identical local losses
    ref = jax.grad(ref_loss)(ws) * S
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# --- expert parallelism ---------------------------------------------------

def test_moe_all_to_all_matches_reference():
    n, e, t, d, f = 4, 8, 32, 16, 24
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(n * t, d).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    w1 = jnp.asarray(rng.randn(e, d, f).astype(np.float32) * 0.2)
    w2 = jnp.asarray(rng.randn(e, f, d).astype(np.float32) * 0.2)
    mesh = Mesh(np.asarray(_devices(n)), ('data',))
    # ample capacity (>= local tokens) so no token is dropped and the
    # sharded program must agree with the dense oracle exactly
    cf = float(e)
    fn = shard_map(
        functools.partial(moe_ffn_local, axis_name='data',
                          capacity_factor=cf),
        mesh=mesh,
        in_specs=(P('data'), P(), P('data'), P('data')),
        out_specs=(P('data'), {'balance_loss': P(), 'drop_frac': P()}),
        check_vma=False)
    got, got_aux = fn(x, gate_w, w1, w2)
    assert float(got_aux['drop_frac']) == 0.0
    # oracle shard-by-shard (capacity is per-shard in the sharded run)
    # same per-expert capacity as the sharded run: capacity is computed
    # from local token count and GLOBAL expert count in both cases
    refs = [moe_ffn_reference(x[i * t:(i + 1) * t], gate_w, w1, w2,
                              capacity_factor=cf)[0]
            for i in range(n)]
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(jnp.concatenate(refs)),
                               rtol=1e-4, atol=1e-5)


def test_moe_drops_over_capacity():
    # capacity 1 with all tokens routed to one expert: only 1 kept
    d, f = 4, 8
    x = jnp.ones((6, d), jnp.float32)
    gate_w = jnp.zeros((d, 2), jnp.float32).at[:, 0].set(1.0)
    w1 = jnp.ones((2, d, f), jnp.float32)
    w2 = jnp.ones((2, f, d), jnp.float32)
    out, aux = moe_ffn_reference(x, gate_w, w1, w2, capacity_factor=1.0 / 3)
    nonzero_rows = (np.abs(np.asarray(out)).sum(-1) > 0).sum()
    assert nonzero_rows == 1
    # 5 of 6 tokens dropped; all routed to expert 0 of 2 -> balance = 2*1*1
    np.testing.assert_allclose(float(aux['drop_frac']), 5.0 / 6, atol=1e-6)
    assert float(aux['balance_loss']) > 1.5


# --- composed transformer step -------------------------------------------

def _make_inputs(cfg, batch, seed=3):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, cfg.vocab_size, (batch, cfg.seq_len))
    labels = rng.randint(0, cfg.vocab_size, (batch, cfg.seq_len))
    return jnp.asarray(tokens, jnp.int32), jnp.asarray(labels, jnp.int32)


@pytest.mark.parametrize('pp,dp,sp,tp,experts', [
    (2, 2, 2, 1, 0),    # pipeline + data + ring-attention sequence
    (2, 2, 2, 1, 4),    # + switch-MoE experts over the data axis
    (2, 1, 1, 4, 0),    # pipeline + 4-way tensor parallel
])
def test_transformer_step_matches_oracle(pp, dp, sp, tp, experts):
    # ample MoE capacity: the sharded run routes per (data, seq) shard
    # per microbatch while the oracle routes the whole batch, so only a
    # drop-free setting is exactly comparable
    cfg = tfm.TransformerConfig(
        vocab_size=32, d_model=16, num_heads=4, d_ff=32,
        num_stages=pp, seq_len=16, num_experts=experts,
        num_microbatches=2, attn='ring',
        capacity_factor=float(max(experts, 1) * 8),
        # the sharded run computes the balance loss per shard, the oracle
        # over the whole batch — only the weight-0 loss is exactly equal;
        # the aux-loss path has its own dedicated tests below
        balance_loss_weight=0.0)
    mesh = tfm.build_transformer_mesh(8, pp, dp, sp, tp,
                                      devices=_devices(8))
    rng = np.random.RandomState(4)
    params = tfm.init_params(rng, cfg)
    batch = 4
    tokens, labels = _make_inputs(cfg, batch)

    step = tfm.make_train_step(cfg, mesh, lr=0.1)
    new_params, loss, aux = step(params, tokens, labels)
    if experts:
        assert float(aux['balance_loss']) >= 0.99   # >= 1 at uniform
        assert 0.0 <= float(aux['drop_frac']) <= 1.0

    ref_loss = tfm.reference_loss(params, tokens, labels, cfg)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-4, atol=1e-5)

    ref_grads = jax.grad(
        lambda p: tfm.reference_loss(p, tokens, labels, cfg))(params)
    ref_new = jax.tree.map(lambda w, g: w - 0.1 * g, params, ref_grads)
    flat_got = jax.tree.leaves_with_path(new_params)
    flat_ref = dict(jax.tree.leaves_with_path(ref_new))
    for path, got in flat_got:
        ref = flat_ref[path]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-3, atol=2e-4,
            err_msg=f'param mismatch at {jax.tree_util.keystr(path)}')


def test_transformer_loss_decreases():
    cfg = tfm.TransformerConfig(vocab_size=16, d_model=16, num_heads=2,
                                d_ff=32, num_stages=2, seq_len=8,
                                num_microbatches=2)
    mesh = tfm.build_transformer_mesh(8, 2, 2, 2, 1, devices=_devices(8))
    rng = np.random.RandomState(5)
    params = tfm.init_params(rng, cfg)
    tokens, _ = _make_inputs(cfg, 4)
    labels = tokens   # learnable target: predict the input token
    step = tfm.make_train_step(cfg, mesh, lr=0.2)
    losses = []
    for _ in range(10):
        params, loss, _aux = step(params, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_remat_matches_no_remat():
    """cfg.remat recomputes block activations in backward; the math must
    be identical — same loss AND same updated params on the full 4-axis
    mesh (collectives replay under jax.checkpoint)."""
    kw = dict(vocab_size=32, d_model=16, num_heads=4, d_ff=32,
              num_stages=2, seq_len=16, num_microbatches=2, attn='ring')
    mesh = tfm.build_transformer_mesh(8, 2, 1, 2, 2, devices=_devices(8))
    rng = np.random.RandomState(11)
    params = tfm.init_params(rng, tfm.TransformerConfig(**kw))
    tokens, labels = _make_inputs(tfm.TransformerConfig(**kw), 4)
    outs = {}
    for remat in (False, True):
        cfg = tfm.TransformerConfig(remat=remat, **kw)
        step = tfm.make_train_step(cfg, mesh, lr=0.1)
        new_params, loss, _aux = step(jax.tree.map(jnp.copy, params),
                                      tokens, labels)
        outs[remat] = (new_params, float(loss))
    assert outs[False][1] == pytest.approx(outs[True][1], rel=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b), rtol=1e-6),
        outs[False][0], outs[True][0])


def test_local_attn_rejected_on_seq_mesh():
    cfg = tfm.TransformerConfig(num_stages=2, attn='local')
    mesh = tfm.build_transformer_mesh(8, 2, 2, 2, 1, devices=_devices(8))
    with pytest.raises(ValueError, match='block-diagonal'):
        tfm.make_train_step(cfg, mesh)


def test_moe_balance_loss_fights_collapse():
    """With the Switch aux loss weighted in, a gate initialized to send
    every token to one expert spreads out; with weight 0 it stays
    collapsed (single-device oracle, differentiable-through-P_e check)."""
    d, f, e, t = 8, 16, 4, 64
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    x = x.at[:, 0].set(jnp.abs(x[:, 0]) + 1.0)   # feature 0 always positive
    w1 = jnp.asarray(rng.randn(e, d, f).astype(np.float32) * 0.2)
    w2 = jnp.asarray(rng.randn(e, f, d).astype(np.float32) * 0.2)
    gate0 = jnp.zeros((d, e), jnp.float32).at[0, 0].set(4.0)

    def max_route_frac(gate_w):
        probs = jax.nn.softmax(x @ gate_w, axis=-1)
        sel = jax.nn.one_hot(jnp.argmax(probs, -1), e)
        return float(sel.mean(0).max())

    def run(weight):
        gate_w = gate0
        for _ in range(50):
            def loss(gw):
                out, aux = moe_ffn_reference(x, gw, w1, w2,
                                             capacity_factor=2.0)
                return (out ** 2).mean() + weight * aux['balance_loss']
            gate_w = gate_w - 1.0 * jax.grad(loss)(gate_w)
        return max_route_frac(gate_w)

    assert max_route_frac(gate0) == 1.0          # starts collapsed
    assert run(0.0) > 0.9, 'control: no pressure, stays collapsed'
    assert run(1.0) < 0.6, 'aux loss failed to spread experts'


def test_sharded_checkpoint_roundtrip(tmp_path):
    """Sharded orbax checkpointing of the 4D-parallel transformer: save
    after training, restore onto a fresh mesh layout, bitwise-equal
    params, and training continues from the restored state."""
    from cxxnet_tpu.nnet.sharded_ckpt import (latest_step, restore_sharded,
                                              save_sharded)
    cfg = tfm.TransformerConfig(vocab_size=16, d_model=16, num_heads=2,
                                d_ff=32, num_stages=2, seq_len=8,
                                num_microbatches=2)
    mesh = tfm.build_transformer_mesh(8, 2, 2, 2, 1, devices=_devices(8))
    rng = np.random.RandomState(6)
    params = tfm.init_params(rng, cfg)
    tokens, _ = _make_inputs(cfg, 4)
    step = tfm.make_train_step(cfg, mesh, lr=0.2)
    for _ in range(3):
        params, loss, _aux = step(params, tokens, tokens)
    save_sharded(str(tmp_path / 'ck'), 2, params)
    assert latest_step(str(tmp_path / 'ck')) == 2

    fresh = tfm.init_params(np.random.RandomState(99), cfg)
    like = tfm.abstract_params(fresh, cfg, mesh)
    restored, got_step = restore_sharded(str(tmp_path / 'ck'), like)
    assert got_step == 2
    for (pa, a), (pb, b) in zip(jax.tree.leaves_with_path(params),
                                jax.tree.leaves_with_path(restored)):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training continues from the restored state identically
    p1, l1, _ = step(params, tokens, tokens)
    p2, l2, _ = step(restored, tokens, tokens)
    assert float(l1) == float(l2)


def test_param_shapes_matches_init_params():
    """param_shapes (the allocation-free resume target) must track
    init_params exactly."""
    for experts in (0, 4):
        cfg = tfm.TransformerConfig(vocab_size=16, d_model=16, num_heads=2,
                                    d_ff=32, num_stages=2, seq_len=8,
                                    num_experts=experts)
        live = tfm.init_params(np.random.RandomState(0), cfg)
        shapes = tfm.param_shapes(cfg)
        la = jax.tree.leaves_with_path(live)
        lb = dict(jax.tree.leaves_with_path(shapes))
        assert len(la) == len(lb)
        for path, leaf in la:
            assert lb[path].shape == leaf.shape, path
            assert lb[path].dtype == leaf.dtype, path


def test_bench_transformer_throughput_smoke(monkeypatch, capsys):
    """bench.py's transformer mode end-to-end at toy size: the scan-in-jit
    K-vs-1 quotient path must emit one valid JSON line with positive
    tokens/sec (the on-chip run reuses this exact code at GPT-2-small
    size)."""
    import json as _json

    import bench

    monkeypatch.setenv('CXXNET_BENCH_STEPS', '3')
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, num_heads=2,
                                d_ff=64, num_stages=2, seq_len=16,
                                attn='local', causal=True,
                                num_microbatches=1, dtype=jnp.float32)
    assert bench._transformer_throughput(
        cfg, batch=2, metric='transformer_tokens_per_sec_per_chip',
        baseline=1.0) == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = _json.loads(line)
    assert out['metric'] == 'transformer_tokens_per_sec_per_chip'
    assert out['unit'] == 'tokens/sec'
    assert out['value'] and out['value'] > 0


def test_multi_train_step_matches_mesh_step():
    """The mirror-contract guard: make_multi_train_step (scanned
    reference_loss + SGD) applied for ONE step must produce the same loss
    and updated params as make_train_step on the composed pp2-dp2-sp2
    mesh (the gradient tie makes that the gradient of the same
    global-mean loss) — if an optimizer change lands in _make_step_body
    but not in the multi-step loop (or vice versa), this is the test
    that breaks."""
    cfg = tfm.TransformerConfig(vocab_size=32, d_model=16, num_heads=2,
                                d_ff=32, num_stages=2, seq_len=8,
                                num_microbatches=2, dtype=jnp.float32)
    mesh = tfm.build_transformer_mesh(8, 2, 2, 2, 1, devices=_devices(8))
    rng = np.random.RandomState(7)
    params_a = tfm.init_params(np.random.RandomState(0), cfg)
    params_b = tfm.init_params(np.random.RandomState(0), cfg)
    tok = jnp.asarray(rng.randint(0, 32, (4, 8)), jnp.int32)
    lab = jnp.asarray(rng.randint(0, 32, (4, 8)), jnp.int32)

    step = tfm.make_train_step(cfg, mesh, lr=0.05)
    new_a, loss_a, _ = step(params_a, tok, lab)

    multi = tfm.make_multi_train_step(cfg, 1, lr=0.05)
    new_b, loss_b = multi(params_b, tok[None], lab[None])

    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for (pa, a), (pb, b) in zip(jax.tree.leaves_with_path(new_a),
                                jax.tree.leaves_with_path(new_b)):
        assert pa == pb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
