"""grafttune: ledger-driven autotuner suite (``-m tune``).

The properties this suite pins down (doc/autotune.md):

* the ``autotune=`` grammar parse/describe round-trips exactly, every
  malformed spelling is a typed ``TuneSpecError`` at parse time, and a
  spec can never escape the :data:`~cxxnet_tpu.tune.KNOBS` declared-safe
  envelope;
* stage 1 prunes from ledger numbers alone — pruned candidates never
  execute, and the receipt stamps the bytes that killed them;
* the search is deterministic: same (spec, seed, probe results) yields a
  byte-identical ``tuned_<task>.conf``, the default candidate is always
  measured first, and an exact tie goes to the baseline;
* a run driven by the tuned artifact is a bitwise twin of the same
  config written by hand (through the real ExecutionPlan path);
* the online :class:`~cxxnet_tpu.tune.TuneController` only re-plans
  inside declared bounds, and its recompile-storm guard vetoes a move
  BEFORE compiling — the ledger's storm sentinel never fires;
* doc/autotune.md's grammar + knob tables cannot drift from the code.
"""

import json
import threading

import numpy as np
import pytest

from cxxnet_tpu.obs import programs
from cxxnet_tpu.runtime import faults
from cxxnet_tpu.serve.autoscale import BREACHED, OK, worst_verdict
from cxxnet_tpu.tune import (KNOBS, LedgerGate, TuneController, TuneSearch,
                             TuneSpace)

from test_device_normalize import assert_params_equal, snap_params
from test_execution_plan import _run_windowed, _trainer
from test_io_perf import _mlp_batches

pytestmark = pytest.mark.tune

SPEC = ('knobs=steps_per_dispatch:1..8,nworker:1..4;budget=30;seed=7;'
        'probe_steps=4;probe_repeats=1')


# --- the autotune= grammar -------------------------------------------------

def test_parse_describe_roundtrip():
    space = TuneSpace.parse(SPEC)
    assert space.mode == 'train' and space.budget == 30.0
    assert space.seed == 7 and space.probe_steps == 4
    again = TuneSpace.parse(space.describe())
    assert again == space
    assert again.describe() == space.describe()


def test_parse_defaults_and_full_range_knob():
    space = TuneSpace.parse('knobs=slots')
    assert space.knob_range('slots').lo == KNOBS['slots'].lo
    assert space.knob_range('slots').hi == KNOBS['slots'].hi
    assert space.budget == 60.0 and space.headroom == 0.1
    assert space.compile_budget == 8 and space.mem_mb == 0.0


def test_mem_knobs_follow_registry():
    space = TuneSpace.parse('knobs=steps_per_dispatch:1..4,nworker:1..4')
    assert space.mem_knobs() == ('steps_per_dispatch',)


@pytest.mark.parametrize('bad', [
    'knobs=warp_speed:1..8',                  # unknown knob
    'knobs=steps_per_dispatch:1..999',        # escapes declared envelope
    'knobs=spec_k:-1..4',                     # below declared floor
    'knobs=slots:8..2',                       # empty range
    'knobs=slots:a..b',                       # non-integer range
    'knobs=slots,slots',                      # knob listed twice
    'knobs=',                                 # nothing to tune
    'budget=30',                              # no knobs= at all
    'knobs=slots;budget=30;budget=60',        # duplicate key
    'knobs=slots;vibe=high',                  # unknown key
    'knobs=slots;mode=predict',               # unknown mode
    'knobs=slots;budget=0',                   # budget must be > 0
    'knobs=slots;headroom=1.5',               # headroom in [0, 1)
    'knobs=slots;probe_steps=0',              # probes must be >= 1
    'knobs=slots;budget=abc',                 # unparseable value
    'knobs=slots;;budget',                    # malformed segment
])
def test_malformed_specs_are_typed_errors(bad):
    with pytest.raises(faults.TuneSpecError):
        TuneSpace.parse(bad)


def test_ladder_is_endpoints_plus_powers_of_two():
    space = TuneSpace.parse('knobs=steps_per_dispatch:1..8,slots:3..12')
    assert space.ladder('steps_per_dispatch') == (1, 2, 4, 8)
    assert space.ladder('slots') == (3, 4, 8, 12)
    with pytest.raises(faults.TuneSpecError):
        space.ladder('pages')


# --- stage 1: the ledger gate ----------------------------------------------

def test_gate_prices_mem_knobs_linearly_and_prunes():
    gate = LedgerGate(base_bytes=100.0, ceiling_bytes=350.0,
                      baseline={'slots': 2, 'nworker': 1},
                      mem_knobs=('slots',))
    assert gate.predicted_bytes({'slots': 4}) == 200.0
    ok, info = gate.admit({'slots': 4, 'nworker': 8})   # nworker is free
    assert ok and 'pruned' not in info
    ok, info = gate.admit({'slots': 8})
    assert not ok and info['pruned'] == 'ledger_bytes_over_ceiling'
    assert info['predicted_bytes'] == 400
    assert info['ceiling_bytes'] == 350


def test_gate_consults_budgeter_and_feasibility():
    class Budgeter:
        def over_budget(self, extra):
            return extra > 50

    gate = LedgerGate(base_bytes=100.0, ceiling_bytes=0.0,
                      baseline={'slots': 1}, mem_knobs=('slots',),
                      budgeter=Budgeter(),
                      feasible=lambda c: 'odd_slots' if c['slots'] == 3
                      else None)
    assert gate.admit({'slots': 1})[0]                  # no extra bytes
    ok, info = gate.admit({'slots': 2})                 # +100 > 50
    assert not ok and info['pruned'] == 'memory_budgeter'
    gate.budgeter = None
    ok, info = gate.admit({'slots': 3})
    assert not ok and info['pruned'] == 'odd_slots'


# --- stage 2: the measured search ------------------------------------------

def _fake_probe(table):
    def probe(cand):
        return table[cand['steps_per_dispatch']]
    return probe


def test_search_prunes_then_measures_and_picks_best():
    space = TuneSpace.parse('knobs=steps_per_dispatch:1..8;budget=30;'
                            'seed=3')
    gate = LedgerGate(base_bytes=100.0, ceiling_bytes=500.0,
                      baseline={'steps_per_dispatch': 1},
                      mem_knobs=('steps_per_dispatch',))
    res = TuneSearch(space, _fake_probe({1: 10.0, 2: 20.0, 4: 40.0}),
                     gate=gate).run('train')
    assert res.stage1_candidates == 4                   # 1, 2, 4, 8
    assert res.stage1_pruned == 1                       # 8 prices at 800
    assert res.measured == 3 and res.failed == 0
    assert res.best == {'steps_per_dispatch': 4}
    assert res.baseline == {'steps_per_dispatch': 1}
    assert res.speedup == pytest.approx(4.0)
    assert res.budget_honored
    pruned = [p for p in res.probes if p.get('pruned')]
    assert len(pruned) == 1 and pruned[0]['stage'] == 1
    assert pruned[0]['ledger']['pruned'] == 'ledger_bytes_over_ceiling'
    assert 'value' not in pruned[0]                     # never executed


def test_search_measures_baseline_first_and_ties_go_to_it():
    space = TuneSpace.parse('knobs=steps_per_dispatch:1..4;budget=30')
    seen = []

    def probe(cand):
        seen.append(cand['steps_per_dispatch'])
        return 5.0                                      # dead heat

    res = TuneSearch(space, probe).run('train')
    assert seen[0] == 1                                 # default first
    assert res.best == res.baseline                     # never churn on 0
    assert res.speedup == 1.0


def test_search_records_probe_failures_and_keeps_going():
    space = TuneSpace.parse('knobs=steps_per_dispatch:1..4;budget=30')
    log = faults.FailureLog()

    def probe(cand):
        if cand['steps_per_dispatch'] == 2:
            raise RuntimeError('device fell over')
        return float(cand['steps_per_dispatch'])

    res = TuneSearch(space, probe, failure_log=log).run('train')
    assert res.failed == 1 and res.measured == 2
    assert res.best == {'steps_per_dispatch': 4}
    recs = log.records('TuneProbeError')
    assert len(recs) == 1 and 'device fell over' in recs[0].detail
    failed = [p for p in res.probes if 'failed' in p]
    assert failed[0]['candidate'] == {'steps_per_dispatch': 2}


def test_search_honors_wall_budget_and_max_probes():
    space = TuneSpace.parse('knobs=steps_per_dispatch:1..8;budget=10')
    t = [0.0]

    def clock():
        t[0] += 6.0                                     # 2 reads per probe
        return t[0]

    res = TuneSearch(space, _fake_probe({1: 1, 2: 2, 4: 4, 8: 8}),
                     clock=clock).run('train')
    assert res.measured == 1                            # baseline only
    assert res.best == res.baseline
    capped = TuneSearch(
        TuneSpace.parse('knobs=steps_per_dispatch:1..8;budget=30;'
                        'max_probes=2'),
        _fake_probe({1: 1, 2: 2, 4: 4, 8: 8})).run('train')
    assert capped.measured == 2


# --- the artifact: byte-deterministic conf + receipt -----------------------

def _search_twice(spec):
    table = {1: 11.0, 2: 17.0, 4: 13.0, 8: 5.0}
    return [TuneSearch(TuneSpace.parse(spec),
                       _fake_probe(table)).run('train')
            for _ in range(2)]


def test_same_seed_spec_yields_byte_identical_conf(tmp_path):
    spec = 'knobs=steps_per_dispatch:1..8;budget=30;seed=11'
    a, b = _search_twice(spec)
    assert a.conf_text() == b.conf_text()
    assert a.best == {'steps_per_dispatch': 2}
    p1, p2 = tmp_path / 'a.conf', tmp_path / 'b.conf'
    a.write_conf(str(p1))
    b.write_conf(str(p2))
    assert p1.read_bytes() == p2.read_bytes()
    text = p1.read_text()
    assert f'# autotune={a.space.describe()}' in text
    assert '# seed=11' in text
    assert 'steps_per_dispatch=2\n' in text


def test_receipt_stamps_counts_probes_and_budget(tmp_path):
    spec = 'knobs=steps_per_dispatch:1..8;budget=30;seed=11'
    res = _search_twice(spec)[0]
    path = tmp_path / 'tuned_train.json'
    res.write_receipt(str(path))
    rec = json.loads(path.read_text())
    assert rec['artifact'] == 'tuned_train.conf'
    assert rec['spec'] == res.space.describe()
    assert rec['counts'] == {'stage1_candidates': 4, 'stage1_pruned': 0,
                             'measured': 4, 'failed': 0}
    assert rec['budget_honored'] is True
    assert rec['best'] == {'steps_per_dispatch': 2}
    assert len(rec['probes']) == 4
    assert all(p['stage'] == 2 for p in rec['probes'])


# --- the tuned config is a bitwise twin of the hand-written one ------------

def test_tuned_artifact_drives_bitwise_twin_of_hand_config():
    """Search with the REAL measured probe (ExecutionPlan round_stepper
    over a dropout MLP), then drive one training run from the artifact's
    knob line and one from the same value written by hand — bitwise."""
    from cxxnet_tpu.nnet.execution import measured_probe

    space = TuneSpace.parse('knobs=steps_per_dispatch:1..2;budget=60;'
                            'probe_steps=4;probe_repeats=1')
    batches = _mlp_batches(n=4)

    def probe(cand):
        return measured_probe(_trainer(), cand['steps_per_dispatch'],
                              batches, repeats=1)

    res = TuneSearch(space, probe).run('train')
    knob_lines = [ln for ln in res.conf_text().splitlines()
                  if ln and not ln.startswith('#')]
    art = dict(ln.split('=', 1) for ln in knob_lines)
    k_art = int(art['steps_per_dispatch'])
    assert k_art in (1, 2)

    tuned, hand = _trainer(), _trainer()
    _run_windowed(tuned, _mlp_batches(n=6), k_art)
    _run_windowed(hand, _mlp_batches(n=6), k_art)
    assert_params_equal(snap_params(tuned), snap_params(hand),
                        rtol=0, atol=0)


# --- the online leg: TuneController ----------------------------------------

def _breach():
    return {'p50': {'state': BREACHED}}


def _ctl(spec, **kw):
    kw.setdefault('hysteresis', 1)
    kw.setdefault('cooldown', 0.0)
    return TuneController(TuneSpace.parse(spec), **kw)


def test_worst_verdict_shared_with_autoscaler():
    assert worst_verdict({}) == OK
    assert worst_verdict({'a': {'state': OK},
                          'b': {'state': BREACHED}}) == BREACHED


def test_bind_rejects_undeclared_knob_and_clamps_bounds():
    ctl = _ctl('knobs=slots:2..8')
    with pytest.raises(faults.TuneSpecError):
        ctl.bind('pages', lambda v: v, 64)
    ctl.bind('slots', lambda v: v, 8, lo=1, hi=64)  # clamped to 2..8
    view = ctl.status_view()['knobs']['slots']
    assert (view['lo'], view['hi']) == (2, 8)


def test_pressure_halves_mem_knobs_toward_declared_floor():
    ctl = _ctl('knobs=slots:1..8', verdicts=_breach)
    moves = []
    ctl.bind('slots', moves.append, 8)
    for i in range(5):
        ctl.evaluate(now=float(i))
    assert moves == [4, 2, 1]                           # floor, then stop
    assert ctl.knob_values()['slots'] == 1


def test_hysteresis_and_cooldown_damp_replanning():
    ctl = _ctl('knobs=slots:1..8', verdicts=_breach, hysteresis=2,
               cooldown=10.0)
    moves = []
    ctl.bind('slots', moves.append, 8)
    assert ctl.evaluate(now=0.0)['applied'] == []       # streak 1 < 2
    assert ctl.evaluate(now=1.0)['applied'] == [('slots', 4)]
    assert ctl.evaluate(now=2.0)['applied'] == []       # inside cooldown
    assert ctl.evaluate(now=20.0)['applied'] == [('slots', 2)]
    assert moves == [4, 2]


def test_headroom_gauge_alone_triggers_shrink():
    ctl = _ctl('knobs=pages:16..64;headroom=0.2',
               gauges=lambda: {'hbm.headroom_frac.dev0': 0.05})
    moves = []
    ctl.bind('pages', moves.append, 64)
    out = ctl.evaluate(now=0.0)
    assert out['direction'] == -1 and out['headroom'] == 0.05
    assert moves == [32]


def test_high_accept_low_mfu_grows_spec_k():
    feed = {'decode.spec_accept_rate': 0.9, 'train.mfu': 0.1}
    ctl = _ctl('knobs=spec_k:0..8', gauges=lambda: dict(feed))
    moves = []
    ctl.bind('spec_k', moves.append, 1)
    ctl.evaluate(now=0.0)
    assert moves == [2]
    feed['train.mfu'] = 0.9                             # chip busy: stop
    assert ctl.evaluate(now=1.0)['applied'] == []


def test_recompile_veto_fires_before_the_setter():
    class Prog:
        name = 'tune.fake'

        def __init__(self, head):
            self.head = head

        def compile_headroom(self):
            return self.head

    log = faults.FailureLog()
    ctl = _ctl('knobs=slots:1..8;compile_budget=8', verdicts=_breach,
               failure_log=log)
    moves = []
    ctl.bind('slots', moves.append, 8, program=Prog(head=0))
    out = ctl.evaluate(now=0.0)
    assert out['applied'] == [] and moves == []         # setter never ran
    assert ctl.compiles() == 0
    recs = log.records('TuneRecompileVetoError')
    assert len(recs) == 1 and 'tune.fake' in recs[0].detail
    assert ctl.status_view()['vetoes'] == 1


def test_space_compile_budget_caps_total_replans():
    log = faults.FailureLog()
    ctl = _ctl('knobs=slots:1..64;compile_budget=2', verdicts=_breach,
               failure_log=log)
    ctl.bind('slots', lambda v: v, 64, recompiles=True)
    for i in range(6):
        ctl.evaluate(now=float(i))
    assert ctl.compiles() == 2                          # 64->32->16, veto
    assert ctl.knob_values()['slots'] == 16
    assert len(log.records('TuneRecompileVetoError')) >= 1


def test_ticker_thread_carries_tune_prefix_and_closes():
    ctl = TuneController(TuneSpace.parse('knobs=slots:1..8'),
                         interval=0.02, name='t1')
    try:
        names = [t.name for t in threading.enumerate()]
        assert any(n.startswith('cxxnet-tune-') for n in names)
    finally:
        ctl.close()
    assert not any(t.name.startswith('cxxnet-tune-')
                   for t in threading.enumerate() if t.is_alive())


# --- the recompile-storm guard drill (satellite 3) -------------------------

def test_storm_drill_thrashing_verdicts_never_trip_the_sentinel():
    """Thrash the controller with BREACHED verdicts against a REAL
    ledger program (bound=2) whose setter genuinely recompiles per knob
    value.  The guard must veto before the sentinel's bound is crossed:
    no ``RecompileStormError`` is recorded, compiles stay under both
    budgets, and at least one veto is on the books."""
    led = programs.get_ledger()
    prog = led.program('tune.test_storm', bound=2)
    fn = prog.jit(lambda x: x * 2.0,
                  key_fn=lambda a, _k: f's{a[0].shape[0]}')
    glog = faults.global_failure_log()
    storms_before = len(glog.records('RecompileStormError'))

    ctl = _ctl('knobs=slots:1..64;compile_budget=4', verdicts=_breach)
    ctl.bind('slots', lambda v: fn(np.zeros(v, np.float32)), 64,
             program=prog)
    for i in range(8):                                  # thrash
        ctl.evaluate(now=float(i))

    assert len(glog.records('RecompileStormError')) == storms_before
    assert prog.compiles <= prog.bound                  # sentinel intact
    assert ctl.compiles() <= ctl.space.compile_budget
    assert ctl.status_view()['vetoes'] >= 1
    assert ctl.knob_values()['slots'] == 16             # 64->32->16, stop


# --- doc drift (satellite 5) -----------------------------------------------

def _repo_doc(rel):
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(here, 'doc', rel)) as f:
        return f.read()


def test_autotune_tables_match_keys_and_knob_registry():
    """doc/autotune.md's grammar + knob tables and the code cannot
    drift: every TuneSpace key and every KNOBS row is documented, and
    nothing documented is unregistered (the grammar table is the
    knob table's prefix in the section — same slicing idiom as the
    scenario/autoscale tables)."""
    from cxxnet_tpu.analysis.config_keys import backtick_key, doc_table_rows
    text = _repo_doc('autotune.md')
    key_heading = '### The `autotune=` grammar'
    knob_heading = '### Declared-safe knobs'
    assert key_heading in text and knob_heading in text
    knob_rows = doc_table_rows(text, after=knob_heading)
    key_all = doc_table_rows(text, after=key_heading)
    key_rows = key_all[:len(key_all) - len(knob_rows)]

    def keys(rows, header):
        return {backtick_key(r[0]) for r in rows
                if backtick_key(r[0]) is not None and r[0] != header}

    registered = set(TuneSpace.registered_keys())
    documented = keys(key_rows, 'key')
    assert documented == registered, (
        f'doc minus code: {sorted(documented - registered)}, '
        f'code minus doc: {sorted(registered - documented)}')
    doc_knobs = keys(knob_rows, 'knob')
    assert doc_knobs == set(KNOBS), (
        f'doc minus code: {sorted(doc_knobs - set(KNOBS))}, '
        f'code minus doc: {sorted(set(KNOBS) - doc_knobs)}')


def test_tasks_doc_documents_the_autotune_surface():
    text = _repo_doc('tasks.md')
    assert '`autotune`' in text
    assert 'task=autotune' in text
