"""Wrapper API tests (reference wrapper/cxxnet.py surface)."""

import gzip
import os
import struct

import numpy as np
import pytest

from cxxnet_tpu import wrapper
from tests.test_io import write_mnist

NET_CFG = """
netconfig=start
layer[+1:f1] = fullc:fc1
  nhidden = 16
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 3
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 10
dev = cpu
eta = 0.3
momentum = 0.9
metric = error
"""


def make_iter_cfg(tmp_path):
    pi, pl, img, y = write_mnist(str(tmp_path))
    return f"""
iter = mnist
  path_img = "{pi}"
  path_label = "{pl}"
  batch_size = 10
  silent = 1
iter = end
"""


def test_dataiter_protocol(tmp_path):
    it = wrapper.DataIter(make_iter_cfg(tmp_path))
    with pytest.raises(RuntimeError):
        it.get_data()
    assert it.next()
    assert it.get_data().shape == (10, 1, 1, 64)
    assert it.get_label().shape == (10, 1)
    n = 1
    while it.next():
        n += 1
    assert n == 5
    it.before_first()
    assert it.next()


def test_get_data_applies_deferred_normalize(tmp_path):
    """CXNIOGetData hands out POST-augment float data; under
    device_normalize=1 the wrapper must apply the deferred spec so
    consumers see the same values as the host-normalize path."""
    from tests.test_io import make_img_dataset
    lst = make_img_dataset(str(tmp_path))
    base = f"""
iter = img
  image_list = "{lst}"
  image_root = "{tmp_path}"
  input_shape = 3,16,16
  batch_size = 4
  round_batch = 1
  silent = 1
  mean_value = 120,118,122
  scale = 0.0078125
"""
    host = wrapper.DataIter(base + "iter = end\n")
    dev = wrapper.DataIter(base + "  device_normalize = 1\niter = end\n")
    assert host.next() and dev.next()
    np.testing.assert_allclose(dev.get_data(), host.get_data(),
                               rtol=0, atol=1e-5)
    assert dev.value.data.dtype == np.uint8      # wire stays uint8


def test_net_train_eval_weights(tmp_path):
    it = wrapper.DataIter(make_iter_cfg(tmp_path))
    net = wrapper.Net(dev='cpu', cfg=NET_CFG)
    net.init_model()
    for r in range(3):
        net.start_round(r)
        it.before_first()
        while it.next():
            net.update(it)
    res = net.evaluate(it, 'test')
    assert 'test-error' in res
    # weight access in reference disk layout: (nhidden, nin)
    w = net.get_weight('fc1', 'wmat')
    assert w.shape == (16, 64)
    b = net.get_weight('fc1', 'bias')
    assert b.shape == (16,)
    # roundtrip set_weight
    net.set_weight(w * 0.5, 'fc1', 'wmat')
    np.testing.assert_allclose(net.get_weight('fc1', 'wmat'), w * 0.5,
                               rtol=1e-6)


def test_net_update_numpy_and_predict():
    rng = np.random.RandomState(0)
    x = rng.randn(10, 1, 1, 64).astype(np.float32)
    y = rng.randint(0, 3, 10).astype(np.float32)
    net = wrapper.train(NET_CFG, x, y, 3, {'eta': 0.1})
    pred = net.predict(x)
    assert pred.shape == (10,)
    feat = net.extract(x, 'f1')
    assert feat.shape == (10, 16)


def test_model_file_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(10, 1, 1, 64).astype(np.float32)
    y = rng.randint(0, 3, 10).astype(np.float32)
    net = wrapper.train(NET_CFG, x, y, 1, {})
    path = str(tmp_path / 'm.model')
    net.save_model(path)
    net2 = wrapper.Net(dev='cpu', cfg=NET_CFG)
    net2.load_model(path)
    np.testing.assert_allclose(net.get_weight('fc1', 'wmat'),
                               net2.get_weight('fc1', 'wmat'), rtol=1e-6)
    np.testing.assert_array_equal(net.predict(x), net2.predict(x))


def test_mnist_wrapper_example_runs(tmp_path):
    """example/MNIST/mnist.py (the reference's Python-API walkthrough)
    runs end-to-end against synthetic idx data."""
    import gzip
    import struct
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rng = np.random.RandomState(0)
    d = tmp_path / 'data'
    d.mkdir()
    for name, n in (('train', 300), ('t10k', 100)):
        img = np.zeros((n, 28, 28), np.uint8)
        y = rng.randint(0, 10, n).astype(np.uint8)
        for i in range(n):
            img[i, y[i] * 2:(y[i] + 1) * 2, :] = 200
        with gzip.open(d / f'{name}-images-idx3-ubyte.gz', 'wb') as f:
            f.write(struct.pack('>iiii', 2051, n, 28, 28))
            f.write(img.tobytes())
        with gzip.open(d / f'{name}-labels-idx1-ubyte.gz', 'wb') as f:
            f.write(struct.pack('>ii', 2049, n))
            f.write(y.tobytes())
    env = dict(os.environ)
    env['PYTHONPATH'] = repo + os.pathsep + env.get('PYTHONPATH', '')
    env['JAX_PLATFORMS'] = 'cpu'
    r = subprocess.run(
        [_sys.executable, os.path.join(repo, 'example', 'MNIST', 'mnist.py')],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=240)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert 'eval-error=' in r.stdout and 'eval-error-after=' in r.stdout
    first = float(r.stdout.split('eval-error=')[1].splitlines()[0])
    after = float(r.stdout.split('eval-error-after=')[1].splitlines()[0])
    assert after <= first
