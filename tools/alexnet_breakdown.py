#!/usr/bin/env python
"""Per-layer time breakdown of a model-zoo train step on the real chip.

    python tools/alexnet_breakdown.py [--model alexnet] [--batch 256]
                                      [--json out.json]

``--model googlenet`` attributes the inception towers (the MFU-0.12
question); ``alexnet`` is the default and the historical name.

The jax profiler cannot trace through the remote (axon) tunnel, so this
tool derives the MFU breakdown directly: it times the full optimizer step
(trainer.compile_multi_step — the whole K-step loop in one dispatch), the
forward pass, and each parameterized/pooling/LRN layer in isolation
(jitted at its exact activation shape, fwd and fwd+bwd).  All timings
loop on-device inside one jit with the dispatch cost cancelled (see
chiptime.py — per-dispatch timing bottoms out at the ~7 ms tunnel RTT).
Layer times are lower bounds (isolated kernels skip fusion
opportunities) but name where the step's time goes — the evidence the
MFU question needs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(            # persistent XLA cache — see chiptime.py
    'JAX_COMPILATION_CACHE_DIR',
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 '.jax_cache'))
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '2')

# chiptime FIRST: its preamble imports the cxxnet_tpu platform shim
# before jax — a bare `import jax` hangs on plugin discovery when the
# tunnel is half-down, even for CPU-only runs (this exact tool sat at
# 0 output for 10+ minutes before the ordering mattered)
from chiptime import atomic_receipt_dump, time_op              # noqa: E402

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402


def _time_step_scan(tr, dstack, lstack, iters=10, reps=3):
    """Per-step seconds of the full optimizer step via the trainer's
    scanned multi-step path (iters-vs-1 difference quotient)."""
    m1 = tr.compile_multi_step(1)
    mk = tr.compile_multi_step(iters)

    def run(fn, n):
        return float(np.asarray(tr.update_n_on_device(fn, dstack, lstack, n)))

    run(m1, 1)
    run(mk, iters)
    t1s, tks = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        run(m1, 1)
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run(mk, iters)
        tks.append(time.perf_counter() - t0)
    # min at each endpoint rejects link jitter spikes (see chiptime.py)
    return (min(tks) - min(t1s)) / (iters - 1)


_MODELS = {  # name -> (conf fn name, default batch, input shape)
    'alexnet': ('alexnet_conf', 256, (3, 227, 227)),
    'inception_bn': ('inception_bn_conf', 128, (3, 224, 224)),
    'googlenet': ('googlenet_conf', 128, (3, 224, 224)),
    'vgg16': ('vgg16_conf', 64, (3, 224, 224)),
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument('--model', default='alexnet', choices=sorted(_MODELS))
    ap.add_argument('--batch', type=int, default=None)
    ap.add_argument('--json', default=None)
    ap.add_argument('--dtype', default='bfloat16',
                    choices=('bfloat16', 'float32'),
                    help='float32 for CPU pipe-clean runs — CPU bf16 is '
                         'emulated and minutes-slow per conv')
    args = ap.parse_args()

    from cxxnet_tpu import models
    from cxxnet_tpu.layers import ForwardContext
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    conf_fn, default_bs, shape = _MODELS[args.model]
    bs = args.batch or default_bs
    conf = getattr(models, conf_fn)() + f"""
batch_size = {bs}
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
compute_type = {args.dtype}
"""
    cdtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32
    tr = NetTrainer(parse_config_string(conf))
    tr.init_model()
    rng = np.random.RandomState(0)
    dstack = tr.shard_batch_stack(
        rng.randint(0, 256, (2, bs) + shape, dtype=np.uint8))
    lstack = tr.shard_batch_stack(
        rng.randint(0, 1000, (2, bs, 1)).astype(np.float32), cast=False)
    data, label = dstack[0], lstack[0]

    # Ordering: per-layer rows FIRST (cheap compiles, the attribution
    # value unique to this tool), whole-step anchor LAST — three runs in
    # a row were killed inside the expensive multi-step-scan compile
    # before a single layer row existed.  pct_of_step is filled in once
    # (if) the step time lands; the known-good step time from the
    # bench_alexnet receipt anchors a partial file.
    t_step = t_fwd = step_flops = None
    net = tr.net
    host = jax.device_get(tr.params)
    rows = []

    def dump(partial: bool) -> None:
        # after EVERY layer: a killed/timed-out run must still leave the
        # rows it produced — losing a finished measurement to a
        # round-end kill is the round-3 failure mode the receipts
        # discipline exists to prevent
        atomic_receipt_dump(args.json, {
            'model': args.model, 'batch': bs,
            'step_ms': round(t_step * 1e3, 2) if t_step else None,
            'fwd_ms': round(t_fwd * 1e3, 2) if t_fwd else None,
            'achieved_tflops': round(step_flops / t_step / 1e12, 2)
                               if t_step and step_flops else None,
            'layers': rows}, partial)

    dump(partial=True)
    for i, info in enumerate(net.cfg.layers):
        layer = net.layers[i]
        if layer.type_name in ('relu', 'flatten', 'dropout', 'softmax'):
            continue                      # elementwise: fused in practice
        spec_in = [net.node_specs[j] for j in info.nindex_in]
        xs = []
        for sp in spec_in:
            shape = ((bs, sp.flat_size) if sp.is_mat
                     else (bs, sp.y, sp.x, sp.c))
            xs.append(jnp.asarray(rng.randn(*shape) * 0.1, cdtype))
        lp = {k: jnp.asarray(v) for k, v in
              host.get(str(net.layer_primary[i]), {}).items()}
        ctx = ForwardContext(is_train=True, rng=jax.random.PRNGKey(0),
                             layer_index=i, compute_dtype=cdtype)

        def f(*inputs, _layer=layer, _lp=lp, _ctx=ctx):
            return _layer.forward(_lp, list(inputs), _ctx)[0]

        is_input_layer = 0 in info.nindex_in

        def g(*inputs, _layer=layer, _lp=lp, _ctx=ctx,
              _input_layer=is_input_layer):
            def loss(lp_, ins):
                out = _layer.forward(lp_, list(ins), _ctx)[0]
                return jnp.sum(out.astype(jnp.float32))
            # interior layers: differentiate wrt params AND inputs —
            # training computes both dW and dX there (skipping dX would
            # let XLA dead-code-eliminate ~1/3 of a conv/fullc layer's
            # backward FLOPs).  The input layer gets params-only, like
            # the real step (no dX wrt the data batch).
            if _lp and _input_layer:
                return jax.grad(loss)(_lp, inputs)
            if _lp:
                return jax.grad(loss, argnums=(0, 1))(_lp, inputs)
            return jax.grad(lambda ins: loss(_lp, ins))(inputs)

        name = f'{i:2d} {layer.type_name}:{info.name or ""}'
        print(f'... timing {name.strip()} fwd', flush=True)
        t_f = time_op(f, tuple(xs))
        print(f'... timing {name.strip()} fwd+bwd', flush=True)
        t_g = time_op(g, tuple(xs))
        rows.append({'layer': name.strip(), 'fwd_us': round(t_f * 1e6, 1),
                     'fwd_bwd_us': round(t_g * 1e6, 1)})
        print(f'{name:26s} fwd {t_f * 1e6:9.1f}us   '
              f'fwd+bwd {t_g * 1e6:9.1f}us', flush=True)
        dump(partial=True)

    # --- whole step & forward-only (the expensive compiles) -----------
    print('timing full train step (multi-step scan compile)...',
          flush=True)
    t_step = _time_step_scan(tr, dstack, lstack)
    for r in rows:
        r['pct_of_step'] = round(100 * r['fwd_bwd_us'] / 1e6 / t_step, 1)
    dump(partial=True)      # t_step is the costliest number: persist NOW
    fwd = tr._forward_fn
    params = tr.params
    t_fwd = time_op(lambda d: fwd(params, d, (), 0), (data,))
    step_flops = tr.train_step_flops(data, label)
    print(f'full train step: {t_step * 1e3:8.2f} ms   '
          f'({step_flops / t_step / 1e12:.1f} TFLOP/s achieved)')
    print(f'forward only:    {t_fwd * 1e3:8.2f} ms')
    covered = sum(r['fwd_bwd_us'] for r in rows) / 1e6
    print(f'sum of isolated layers (fwd+bwd): {covered * 1e3:.2f} ms '
          f'of {t_step * 1e3:.2f} ms step '
          f'({100 * covered / t_step:.0f}% — rest is fusion overlap, '
          f'elementwise, optimizer, dispatch)')
    dump(partial=False)
    if args.json:
        print(f'wrote {args.json}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
