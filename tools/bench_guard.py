#!/usr/bin/env python
"""bench_guard — validate the committed BENCH_*.json receipt ledger.

Usage::

    python tools/bench_guard.py [--strict] [--tolerance F] [root]

Every committed receipt is a measurement the trajectory's claims stand
on, so the guard enforces the rules the bench modes promise
(doc/benchmarks.md):

* **strict JSON** — ``NaN``/``Infinity`` are not JSON; an unmeasured
  quantity must be ``null`` (the null-not-NaN rule every receipt
  writer follows since PR 8).  A receipt that fails to parse strictly
  fails the guard.
* **platform stamp** — a measured payload (``value`` not null) must
  say what it was measured ON (``"platform"``: ``tpu`` /
  ``cpu-fallback`` / ...), or a host number could pass as a per-chip
  one.  Receipts committed before the stamp rule are grandfathered in
  ``LEGACY_NO_PLATFORM`` — a shrink-only list: entries may be removed
  as old rounds are re-measured, never added.
* **regression flags** — within a receipt family (``BENCH_SERVE_r03``
  → family ``BENCH_SERVE``), the same metric re-measured in a later
  round is compared: a throughput (``*/sec``) drop or a latency
  (``*ms``) rise beyond ``--tolerance`` (default 30%) is flagged.
  Flags are warnings (exit 0) unless ``--strict`` — cross-round
  hardware may legitimately differ; the stamp says so.

Exit codes: ``0`` clean (or warnings only), ``1`` validation failure
(or flagged regressions under ``--strict``), ``2`` internal error.
``pytest -m obs`` runs the guard over the repo ledger, so a bad
receipt fails tier-1 before it is ever cited.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

#: receipts committed before the platform-stamp rule (PR 5) existed —
#: shrink-only: remove entries as rounds are re-measured, NEVER add
LEGACY_NO_PLATFORM = frozenset({
    'BENCH_IO_r01.json',       # PR 5 host-only io sweep (no device leg)
    'BENCH_r02.json',          # pre-rule driver envelopes
    'BENCH_r03.json',
})

_ROUND_RE = re.compile(r'^(.*)_r(\d+)\.json$')


def _reject_const(tok: str):
    raise ValueError(f'non-strict JSON constant {tok!r} (the '
                     'null-not-NaN rule: unmeasured must be null)')


def load_strict(path: str):
    with open(path, encoding='utf-8') as f:
        return json.load(f, parse_constant=_reject_const)


def payloads(doc) -> List[dict]:
    """Metric payloads inside a receipt file: the file may be one
    payload, a list of payloads, or a driver envelope carrying them
    under ``parsed``."""
    if isinstance(doc, list):
        return [p for p in doc if isinstance(p, dict) and 'metric' in p]
    if not isinstance(doc, dict):
        return []
    if 'metric' in doc:
        return [doc]
    parsed = doc.get('parsed')
    return payloads(parsed) if parsed is not None else []


def check_file(path: str) -> Tuple[List[str], List[dict]]:
    """(errors, payloads) for one receipt file."""
    name = os.path.basename(path)
    try:
        doc = load_strict(path)
    except ValueError as e:
        return [f'{name}: invalid strict JSON: {e}'], []
    errs = []
    loads = payloads(doc)
    for p in loads:
        if p.get('value') is None:
            continue                     # unmeasured/error payload
        if 'platform' not in p and name not in LEGACY_NO_PLATFORM:
            errs.append(
                f'{name}: measured payload {p.get("metric")!r} carries '
                'no "platform" stamp (tpu / cpu-fallback / ...)')
    return errs, loads


def _direction(unit: Optional[str], metric: str) -> int:
    """+1 = higher is better (throughput), -1 = lower is better
    (latency), 0 = not comparable."""
    u = (unit or '').lower()
    if '/sec' in u:
        return 1
    if u == 'ms' or metric.endswith('_ms') or '_ms_' in metric:
        return -1
    return 0


def flag_regressions(rounds: Dict[str, Dict[int, List[dict]]],
                     tolerance: float) -> List[str]:
    """Compare each metric against its most recent PRIOR round within
    the same receipt family; returns human-readable flags."""
    flags = []
    for family, per_round in sorted(rounds.items()):
        seen: Dict[str, Tuple[int, float, Optional[str]]] = {}
        for rnd in sorted(per_round):
            for p in per_round[rnd]:
                metric, value = p.get('metric'), p.get('value')
                if not metric or not isinstance(value, (int, float)):
                    continue
                prior = seen.get(metric)
                if prior is not None:
                    prnd, pval, punit = prior
                    d = _direction(p.get('unit'), metric)
                    if d and punit == p.get('unit') and pval > 0:
                        change = (value - pval) / pval
                        if change * d < -tolerance:
                            flags.append(
                                f'{family}: {metric} '
                                f'{"fell" if d > 0 else "rose"} '
                                f'{abs(change):.0%} from r{prnd:02d} '
                                f'({pval:g}) to r{rnd:02d} ({value:g})')
                seen[metric] = (rnd, float(value), p.get('unit'))
    return flags


def run(root: str, tolerance: float = 0.30,
        strict: bool = False) -> int:
    files = sorted(glob.glob(os.path.join(root, 'BENCH_*.json')))
    if not files:
        print(f'bench_guard: no BENCH_*.json under {root}',
              file=sys.stderr)
        return 1
    errors: List[str] = []
    rounds: Dict[str, Dict[int, List[dict]]] = {}
    for path in files:
        errs, loads = check_file(path)
        errors.extend(errs)
        m = _ROUND_RE.match(os.path.basename(path))
        if m and loads:
            rounds.setdefault(m.group(1), {})[int(m.group(2))] = loads
    flags = flag_regressions(rounds, tolerance)
    for e in errors:
        print(f'ERROR {e}')
    for f in flags:
        print(f'FLAG  {f}')
    ok = len(files) - len({e.split(':')[0] for e in errors})
    print(f'bench_guard: {len(files)} receipts, {ok} clean, '
          f'{len(errors)} error(s), {len(flags)} regression flag(s)')
    if errors:
        return 1
    if flags and strict:
        return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument('root', nargs='?',
                   default=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
    p.add_argument('--strict', action='store_true',
                   help='regression flags fail (exit 1), not just warn')
    p.add_argument('--tolerance', type=float, default=0.30,
                   help='relative change beyond which a re-measured '
                        'metric is flagged (default 0.30)')
    args = p.parse_args(argv)
    try:
        return run(os.path.abspath(args.root), tolerance=args.tolerance,
                   strict=args.strict)
    except Exception:
        import traceback
        traceback.print_exc()
        print('bench_guard: internal error (no verdict)', file=sys.stderr)
        return 2


if __name__ == '__main__':
    sys.exit(main())
