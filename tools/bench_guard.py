#!/usr/bin/env python
"""bench_guard — validate the committed BENCH_*.json receipt ledger.

Usage::

    python tools/bench_guard.py [--strict] [--tolerance F] [root]

Every committed receipt is a measurement the trajectory's claims stand
on, so the guard enforces the rules the bench modes promise
(doc/benchmarks.md):

* **strict JSON** — ``NaN``/``Infinity`` are not JSON; an unmeasured
  quantity must be ``null`` (the null-not-NaN rule every receipt
  writer follows since PR 8).  A receipt that fails to parse strictly
  fails the guard.
* **platform stamp** — a measured payload (``value`` not null) must
  say what it was measured ON (``"platform"``: ``tpu`` /
  ``cpu-fallback`` / ...), or a host number could pass as a per-chip
  one.  Receipts committed before the stamp rule are grandfathered in
  ``LEGACY_NO_PLATFORM`` — a shrink-only list: entries may be removed
  as old rounds are re-measured, never added.
* **regression flags** — within a receipt family (``BENCH_SERVE_r03``
  → family ``BENCH_SERVE``), the same metric re-measured in a later
  round is compared: a throughput (``*/sec``) drop or a latency
  (``*ms``) rise beyond ``--tolerance`` (default 30%) is flagged.
  Flags are warnings (exit 0) unless ``--strict`` — cross-round
  hardware may legitimately differ; the stamp says so.
* **scenario receipts** — a ``BENCH_SCENARIOS_*`` receipt
  (``scenario_autoscale_wins``) is an A/B claim, so its structure is
  validated: at least four scenarios, each with a static AND an
  autoscale leg whose every served stream was twin-checked in-bench,
  the win count consistent with the per-scenario verdicts and at
  least 3, and a composed chaos leg with zero twin violations and
  zero untyped sheds.  Per-leg ``p99_ms``/``loss`` are expanded into
  synthetic payloads so cross-round regression flags cover them.
* **kv-tier receipts** — a ``BENCH_KV_*`` receipt
  (``kv_tier_speedup``) claims the tiered cache beats cold prefill, so
  the guard re-checks the claim's load-bearing structure: the cached
  working set is LARGER than the HBM pool (``cache_pages`` >
  ``hbm_pages`` — otherwise the tiers were never needed), the warm leg
  actually promoted through tier 2 (``kv_promoted_pages`` and
  ``kv.disk_promote_pages`` both positive), every stream in BOTH legs
  was twin-asserted in-bench, and the speedup is at least 2x.  Per-leg
  throughput and promote latency are expanded into synthetic payloads
  for cross-round regression flags.

Exit codes: ``0`` clean (or warnings only), ``1`` validation failure
(or flagged regressions under ``--strict``), ``2`` internal error.
``pytest -m obs`` runs the guard over the repo ledger, so a bad
receipt fails tier-1 before it is ever cited.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

#: receipts committed before the platform-stamp rule (PR 5) existed —
#: shrink-only: remove entries as rounds are re-measured, NEVER add
LEGACY_NO_PLATFORM = frozenset({
    'BENCH_IO_r01.json',       # PR 5 host-only io sweep (no device leg)
    'BENCH_r02.json',          # pre-rule driver envelopes
    'BENCH_r03.json',
})

_ROUND_RE = re.compile(r'^(.*)_r(\d+)\.json$')


def _reject_const(tok: str):
    raise ValueError(f'non-strict JSON constant {tok!r} (the '
                     'null-not-NaN rule: unmeasured must be null)')


def load_strict(path: str):
    with open(path, encoding='utf-8') as f:
        return json.load(f, parse_constant=_reject_const)


def payloads(doc) -> List[dict]:
    """Metric payloads inside a receipt file: the file may be one
    payload, a list of payloads, or a driver envelope carrying them
    under ``parsed``."""
    if isinstance(doc, list):
        return [p for p in doc if isinstance(p, dict) and 'metric' in p]
    if not isinstance(doc, dict):
        return []
    if 'metric' in doc:
        return [doc]
    parsed = doc.get('parsed')
    return payloads(parsed) if parsed is not None else []


SCENARIO_METRIC = 'scenario_autoscale_wins'

#: a scenario receipt must show the autoscaler beating the static
#: baseline on at least this many scenarios — the claim it exists for
SCENARIO_MIN_WINS = 3


def expand_scenarios(p: dict, name: str) -> Tuple[List[str], List[dict]]:
    """Validate one ``scenario_autoscale_wins`` payload and expand its
    per-scenario legs into synthetic payloads for regression flags."""
    errs: List[str] = []
    synth: List[dict] = []
    plat = p.get('platform')
    rows = p.get('scenarios')
    if not isinstance(rows, list) or len(rows) < 4:
        return [f'{name}: scenario receipt carries '
                f'{len(rows) if isinstance(rows, list) else 0} '
                'scenarios (need >= 4)'], []
    wins = 0
    for row in rows:
        rname = row.get('name', '?')
        for leg_name in ('static', 'autoscale'):
            leg = row.get(leg_name)
            if not isinstance(leg, dict):
                errs.append(f'{name}: scenario {rname!r} has no '
                            f'{leg_name!r} leg')
                continue
            if leg.get('twin_checked') != leg.get('served'):
                errs.append(
                    f'{name}: scenario {rname!r} {leg_name} leg '
                    f'twin-checked {leg.get("twin_checked")} of '
                    f'{leg.get("served")} served streams — every '
                    'served stream must be twin-asserted in-bench')
            for key, unit in (('p99_ms', 'ms'), ('loss', 'requests')):
                synth.append({
                    'metric': f'scenario_{rname}_{leg_name}_{key}',
                    'value': leg.get(key), 'unit': unit,
                    'platform': plat})
        wins += bool(row.get('win'))
    if wins != p.get('value'):
        errs.append(f'{name}: win count {p.get("value")} disagrees '
                    f'with per-scenario verdicts ({wins})')
    if wins < SCENARIO_MIN_WINS:
        errs.append(f'{name}: autoscale beat static on only {wins} '
                    f'scenarios (need >= {SCENARIO_MIN_WINS})')
    chaos = p.get('chaos')
    if not isinstance(chaos, dict):
        errs.append(f'{name}: scenario receipt has no composed chaos '
                    'leg')
    else:
        for key in ('twin_violations', 'untyped_sheds'):
            if chaos.get(key) != 0:
                errs.append(f'{name}: chaos leg {key}='
                            f'{chaos.get(key)} (must be 0)')
        if not chaos.get('slow_steps_fired'):
            errs.append(f'{name}: chaos leg fired no faults — it is '
                        'not a chaos leg')
    return errs, synth


KV_METRIC = 'kv_tier_speedup'

#: the tier thesis the receipt exists for: serving a prefix hit through
#: the host/disk tiers must beat re-prefilling it cold by at least 2x
KV_MIN_SPEEDUP = 2.0


def expand_kv_tiers(p: dict, name: str) -> Tuple[List[str], List[dict]]:
    """Validate one ``kv_tier_speedup`` payload and expand its per-leg
    numbers into synthetic payloads for regression flags."""
    errs: List[str] = []
    synth: List[dict] = []
    plat = p.get('platform')
    for leg_name in ('warm', 'cold'):
        leg = p.get(leg_name)
        if not isinstance(leg, dict):
            errs.append(f'{name}: kv receipt has no {leg_name!r} leg')
            continue
        if leg.get('twin_checked') != leg.get('streams'):
            errs.append(
                f'{name}: {leg_name} leg twin-checked '
                f'{leg.get("twin_checked")} of {leg.get("streams")} '
                'streams — every stream must be twin-asserted in-bench')
        synth.append({'metric': f'kv_{leg_name}_tokens_per_sec',
                      'value': leg.get('tokens_per_sec'),
                      'unit': 'tokens/sec', 'platform': plat})
    warm = p.get('warm') if isinstance(p.get('warm'), dict) else {}
    kv = warm.get('kv') if isinstance(warm.get('kv'), dict) else {}
    if not warm.get('kv_promoted_pages') or not kv.get(
            'disk_promote_pages'):
        errs.append(f'{name}: warm leg never promoted through the '
                    'tiers (kv_promoted_pages='
                    f'{warm.get("kv_promoted_pages")}, '
                    f'disk_promote_pages={kv.get("disk_promote_pages")})'
                    ' — the speedup is not a tier claim')
    cache_pages, hbm_pages = p.get('cache_pages'), p.get('hbm_pages')
    if not (isinstance(cache_pages, int) and isinstance(hbm_pages, int)
            and cache_pages > hbm_pages):
        errs.append(f'{name}: cached working set ({cache_pages} pages) '
                    f'does not exceed the HBM pool ({hbm_pages} pages) '
                    '— the bench proves nothing about tiering')
    value = p.get('value')
    if not (isinstance(value, (int, float))
            and value >= KV_MIN_SPEEDUP):
        errs.append(f'{name}: kv_tier_speedup {value} is below the '
                    f'{KV_MIN_SPEEDUP}x claim the receipt exists for')
    for key in ('promote_ms_p50', 'promote_ms_p99'):
        synth.append({'metric': f'kv_{key}', 'value': warm.get(key),
                      'unit': 'ms', 'platform': plat})
    return errs, synth


SHARD_METRIC = 'decode_shard_scaling'

#: the graftshard capacity thesis: at 4 devices (fixed per-device page
#: budget, slots scaling with the mesh) aggregate decode tokens/sec
#: must beat the single-device leg by at least this factor
SHARD_MIN_SCALING = 1.5


def expand_sharded(p: dict, name: str) -> Tuple[List[str], List[dict]]:
    """Validate one ``decode_shard_scaling`` payload and expand its
    per-width legs + disaggregation A/B into synthetic payloads."""
    errs: List[str] = []
    synth: List[dict] = []
    plat = p.get('platform')
    legs = p.get('legs')
    if not isinstance(legs, list) or len(legs) < 2:
        return [f'{name}: shard receipt carries '
                f'{len(legs) if isinstance(legs, list) else 0} '
                'width legs (need >= 2)'], []
    for leg in legs:
        tp = leg.get('tp', '?')
        if leg.get('twin_checked') != leg.get('streams'):
            errs.append(
                f'{name}: tp:{tp} leg twin-checked '
                f'{leg.get("twin_checked")} of {leg.get("streams")} '
                'streams — every stream must be twin-asserted in-bench')
        per = leg.get('resident_bytes_per_device')
        if not (isinstance(per, list) and len(per) == tp
                and all(isinstance(b, int) and b > 0 for b in per)):
            errs.append(f'{name}: tp:{tp} leg resident_bytes_per_device'
                        f'={per!r} does not ledger {tp} devices')
        synth.append({'metric': f'shard_tp{tp}_tokens_per_sec',
                      'value': leg.get('tokens_per_sec'),
                      'unit': 'tokens/sec', 'platform': plat})
    if p.get('twin_violations') != 0:
        errs.append(f'{name}: twin_violations='
                    f'{p.get("twin_violations")} (must be 0)')
    value = p.get('value')
    if legs[-1].get('tp') == 4 and not (
            isinstance(value, (int, float))
            and value >= SHARD_MIN_SCALING):
        errs.append(f'{name}: decode_shard_scaling {value} is below '
                    f'the {SHARD_MIN_SCALING}x claim the receipt '
                    'exists for')
    disagg = p.get('disagg')
    if not isinstance(disagg, dict):
        errs.append(f'{name}: shard receipt has no disaggregation A/B')
    else:
        for leg_name in ('off', 'on'):
            leg = disagg.get(leg_name)
            if not isinstance(leg, dict):
                errs.append(f'{name}: disagg A/B has no {leg_name!r} '
                            'leg')
                continue
            if leg.get('twin_checked') != leg.get('streams'):
                errs.append(
                    f'{name}: disagg {leg_name} leg twin-checked '
                    f'{leg.get("twin_checked")} of '
                    f'{leg.get("streams")} streams')
            synth.append({
                'metric': f'shard_disagg_{leg_name}_short_ttft_p99_ms',
                'value': leg.get('short_ttft_p99_ms'), 'unit': 'ms',
                'platform': plat})
        imp = disagg.get('short_ttft_improvement')
        if not (isinstance(imp, (int, float)) and imp > 1.0):
            errs.append(f'{name}: disaggregation did not improve '
                        f'short-stream TTFT p99 (improvement={imp}) — '
                        'admission past the head-of-line blocker is the '
                        'claim the knob exists for')
    return errs, synth


TUNE_METRIC = 'autotune_speedup'

#: the modes an autotune receipt must cover — the "beats the default on
#: >= 2 bench modes" claim (doc/autotune.md)
TUNE_MODES = ('scan', 'decode')


def expand_autotune(p: dict, name: str) -> Tuple[List[str], List[dict]]:
    """Validate one ``autotune_speedup`` payload and expand its per-mode
    throughputs into synthetic payloads for regression flags."""
    errs: List[str] = []
    synth: List[dict] = []
    plat = p.get('platform')
    modes = p.get('modes')
    if not isinstance(modes, dict):
        return [f'{name}: autotune receipt has no per-mode legs'], []
    speedups = []
    for mode in TUNE_MODES:
        leg = modes.get(mode)
        if not isinstance(leg, dict):
            errs.append(f'{name}: autotune receipt has no {mode!r} leg')
            continue
        sp = leg.get('speedup')
        if not (isinstance(sp, (int, float)) and sp >= 1.0):
            errs.append(f'{name}: {mode} leg speedup {sp} < 1.0 — the '
                        'tuned config must never lose to the default')
        else:
            speedups.append(sp)
        search = leg.get('search')
        if not isinstance(search, dict):
            errs.append(f'{name}: {mode} leg carries no search block')
        else:
            if not search.get('budget_honored') or not (
                    isinstance(search.get('wall_s'), (int, float))
                    and isinstance(search.get('budget_s'), (int, float))
                    and search['wall_s'] <= search['budget_s']):
                errs.append(f'{name}: {mode} search wall '
                            f'{search.get("wall_s")}s broke its declared '
                            f'{search.get("budget_s")}s budget')
            if not (isinstance(search.get('measured'), int)
                    and search['measured'] >= 1):
                errs.append(f'{name}: {mode} search measured no '
                            'candidates')
        for key, unit in (('default_steps_per_sec', 'steps/sec'),
                          ('tuned_steps_per_sec', 'steps/sec'),
                          ('default_tokens_per_sec', 'tokens/sec'),
                          ('tuned_tokens_per_sec', 'tokens/sec')):
            if key in leg:
                synth.append({'metric': f'autotune_{mode}_{key}',
                              'value': leg.get(key), 'unit': unit,
                              'platform': plat})
    if modes.get('scan', {}).get('bitwise_equal') is not True:
        errs.append(f'{name}: scan leg is not bitwise-asserted — the '
                    'speedup could be bought with a semantics drift')
    if modes.get('decode', {}).get('stream_twins') is not True:
        errs.append(f'{name}: decode leg streams were not twin-checked')
    search = p.get('search')
    if not isinstance(search, dict):
        errs.append(f'{name}: autotune receipt has no aggregate search '
                    'block')
    else:
        if not search.get('budget_honored'):
            errs.append(f'{name}: aggregate search broke its declared '
                        'budget')
        if not (isinstance(search.get('stage1_pruned'), int)
                and search['stage1_pruned'] >= 1):
            errs.append(f'{name}: stage 1 pruned nothing '
                        f'({search.get("stage1_pruned")}) — the ledger '
                        'gate never demonstrably gated')
    guard = p.get('storm_guard')
    if not isinstance(guard, dict):
        errs.append(f'{name}: autotune receipt has no storm-guard drill')
    else:
        if guard.get('storm_errors') != 0:
            errs.append(f'{name}: storm-guard drill recorded '
                        f'{guard.get("storm_errors")} RecompileStormError'
                        '(s) — the guard exists to make this 0')
        if not (isinstance(guard.get('compiles'), int)
                and isinstance(guard.get('compile_budget'), int)
                and guard['compiles'] <= guard['compile_budget']):
            errs.append(f'{name}: drill compiles '
                        f'{guard.get("compiles")} exceed the declared '
                        f'budget {guard.get("compile_budget")}')
        if not guard.get('vetoes'):
            errs.append(f'{name}: the drill never vetoed a re-plan — '
                        'it did not exercise the guard')
    value = p.get('value')
    if speedups and isinstance(value, (int, float)) \
            and abs(value - min(speedups)) > 1e-6:
        errs.append(f'{name}: headline {value} is not the worst-mode '
                    f'speedup ({min(speedups)})')
    return errs, synth


CNN_METRIC = 'cnn_fused_speedup'


def expand_cnn_fused(p: dict, name: str) -> Tuple[List[str], List[dict]]:
    """Validate one ``cnn_fused_speedup`` payload (BENCH_CNN — the
    graftfuse A/B, doc/kernels.md): every A/B leg must carry its
    in-bench twin assertion (a speedup over diverging math is not a
    speedup), the micro_batch sweep must be bitwise at every split with
    ledger peak bytes monotone non-increasing in the split, and the
    headline must be the best leg's speedup.  Per-leg throughputs are
    expanded into synthetic payloads for cross-round regression
    flags."""
    errs: List[str] = []
    synth: List[dict] = []
    plat = p.get('platform')
    train = p.get('train')
    if not isinstance(train, dict):
        errs.append(f'{name}: cnn_fused receipt has no train leg')
        train = {}
    elif train.get('twin_ok') is not True:
        errs.append(f'{name}: train leg params were not twin-asserted '
                    '— fused training could have diverged unnoticed')
    infer = p.get('inference')
    if not isinstance(infer, dict):
        errs.append(f'{name}: cnn_fused receipt has no inference leg')
        infer = {}
    else:
        if infer.get('twin_ok') is not True:
            errs.append(f'{name}: inference leg scores were not '
                        'twin-asserted against the unfolded engine')
        fv = infer.get('fold_view')
        if not (isinstance(fv, dict) and fv.get('pairs')):
            errs.append(f'{name}: inference leg folded no conv+BN '
                        'pairs — the A/B measured nothing')
    mb = p.get('micro_batch')
    if not (isinstance(mb, dict)
            and isinstance(mb.get('sweep'), list) and mb['sweep']):
        errs.append(f'{name}: cnn_fused receipt has no micro_batch '
                    'sweep')
    else:
        peaks = []
        for row in mb['sweep']:
            if row.get('bitwise_equal_to_unsplit') is not True:
                errs.append(
                    f'{name}: micro_batch={row.get("micro_batch")} row '
                    'is not bitwise-asserted against the unsplit step')
            if isinstance(row.get('peak_bytes'), int) \
                    and row['peak_bytes'] > 0:
                peaks.append(row['peak_bytes'])
            else:
                errs.append(
                    f'{name}: micro_batch={row.get("micro_batch")} row '
                    'carries no ledger peak_bytes — the split\'s memory '
                    'claim is unsubstantiated')
        if any(a < b for a, b in zip(peaks, peaks[1:])):
            errs.append(f'{name}: micro_batch peak_bytes {peaks} grow '
                        'with the split — splitting must bound peak '
                        'HBM, not inflate it')
    speedups = [leg.get('speedup') for leg in (train, infer)
                if isinstance(leg.get('speedup'), (int, float))]
    value = p.get('value')
    if speedups and isinstance(value, (int, float)) \
            and abs(value - max(speedups)) > 1e-6:
        errs.append(f'{name}: headline {value} is not the best-leg '
                    f'speedup ({max(speedups)})')
    for leg, key, unit in (
            (train, 'fused_steps_per_sec', 'steps/sec'),
            (train, 'unfused_steps_per_sec', 'steps/sec'),
            (infer, 'folded_rows_per_sec', 'rows/sec'),
            (infer, 'plain_rows_per_sec', 'rows/sec')):
        if key in leg:
            synth.append({'metric': f'cnn_fused_{key}',
                          'value': leg.get(key), 'unit': unit,
                          'platform': plat})
    return errs, synth


def check_file(path: str) -> Tuple[List[str], List[dict]]:
    """(errors, payloads) for one receipt file."""
    name = os.path.basename(path)
    try:
        doc = load_strict(path)
    except ValueError as e:
        return [f'{name}: invalid strict JSON: {e}'], []
    errs = []
    loads = payloads(doc)
    extra: List[dict] = []               # synthetic, never re-scanned
    for p in loads:
        if p.get('value') is None:
            continue                     # unmeasured/error payload
        if 'platform' not in p and name not in LEGACY_NO_PLATFORM:
            errs.append(
                f'{name}: measured payload {p.get("metric")!r} carries '
                'no "platform" stamp (tpu / cpu-fallback / ...)')
        if p.get('metric') == SCENARIO_METRIC:
            s_errs, synth = expand_scenarios(p, name)
            errs.extend(s_errs)
            extra.extend(synth)
        elif p.get('metric') == KV_METRIC:
            k_errs, synth = expand_kv_tiers(p, name)
            errs.extend(k_errs)
            extra.extend(synth)
        elif p.get('metric') == SHARD_METRIC:
            s_errs, synth = expand_sharded(p, name)
            errs.extend(s_errs)
            extra.extend(synth)
        elif p.get('metric') == TUNE_METRIC:
            t_errs, synth = expand_autotune(p, name)
            errs.extend(t_errs)
            extra.extend(synth)
        elif p.get('metric') == CNN_METRIC:
            c_errs, synth = expand_cnn_fused(p, name)
            errs.extend(c_errs)
            extra.extend(synth)
    return errs, loads + extra


def _direction(unit: Optional[str], metric: str) -> int:
    """+1 = higher is better (throughput), -1 = lower is better
    (latency), 0 = not comparable."""
    u = (unit or '').lower()
    if '/sec' in u:
        return 1
    if u == 'ms' or metric.endswith('_ms') or '_ms_' in metric:
        return -1
    if metric.endswith(('_loss', '_shed')):
        return -1                        # lost/shed requests: fewer wins
    return 0


def flag_regressions(rounds: Dict[str, Dict[int, List[dict]]],
                     tolerance: float) -> List[str]:
    """Compare each metric against its most recent PRIOR round within
    the same receipt family; returns human-readable flags."""
    flags = []
    for family, per_round in sorted(rounds.items()):
        seen: Dict[str, Tuple[int, float, Optional[str]]] = {}
        for rnd in sorted(per_round):
            for p in per_round[rnd]:
                metric, value = p.get('metric'), p.get('value')
                if not metric or not isinstance(value, (int, float)):
                    continue
                prior = seen.get(metric)
                if prior is not None:
                    prnd, pval, punit = prior
                    d = _direction(p.get('unit'), metric)
                    if d and punit == p.get('unit') and pval > 0:
                        change = (value - pval) / pval
                        if change * d < -tolerance:
                            flags.append(
                                f'{family}: {metric} '
                                f'{"fell" if d > 0 else "rose"} '
                                f'{abs(change):.0%} from r{prnd:02d} '
                                f'({pval:g}) to r{rnd:02d} ({value:g})')
                seen[metric] = (rnd, float(value), p.get('unit'))
    return flags


def run(root: str, tolerance: float = 0.30,
        strict: bool = False) -> int:
    files = sorted(glob.glob(os.path.join(root, 'BENCH_*.json')))
    if not files:
        print(f'bench_guard: no BENCH_*.json under {root}',
              file=sys.stderr)
        return 1
    errors: List[str] = []
    rounds: Dict[str, Dict[int, List[dict]]] = {}
    for path in files:
        errs, loads = check_file(path)
        errors.extend(errs)
        m = _ROUND_RE.match(os.path.basename(path))
        if m and loads:
            rounds.setdefault(m.group(1), {})[int(m.group(2))] = loads
    flags = flag_regressions(rounds, tolerance)
    for e in errors:
        print(f'ERROR {e}')
    for f in flags:
        print(f'FLAG  {f}')
    ok = len(files) - len({e.split(':')[0] for e in errors})
    print(f'bench_guard: {len(files)} receipts, {ok} clean, '
          f'{len(errors)} error(s), {len(flags)} regression flag(s)')
    if errors:
        return 1
    if flags and strict:
        return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument('root', nargs='?',
                   default=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
    p.add_argument('--strict', action='store_true',
                   help='regression flags fail (exit 1), not just warn')
    p.add_argument('--tolerance', type=float, default=0.30,
                   help='relative change beyond which a re-measured '
                        'metric is flagged (default 0.30)')
    args = p.parse_args(argv)
    try:
        return run(os.path.abspath(args.root), tolerance=args.tolerance,
                   strict=args.strict)
    except Exception:
        import traceback
        traceback.print_exc()
        print('bench_guard: internal error (no verdict)', file=sys.stderr)
        return 2


if __name__ == '__main__':
    sys.exit(main())
