"""On-chip op timing over a high-latency dispatch link (the axon tunnel).

Per-call dispatch over the tunnel costs ~7 ms round-trip and does NOT
pipeline, so any timing that issues one dispatch per measured call bottoms
out at the link latency regardless of the op: a 19-GFLOP matmul and a
2-GFLOP matmul both "measure" ~7.3 ms (this is exactly what the first
round of micro receipts showed — every entry pinned to the same floor).

The only valid measurement runs the op N times inside ONE jitted
computation and divides out N:

    t_per_iter = (t(loop_N) - t(loop_1)) / (N - 1)

which cancels the constant dispatch/link cost exactly.  The loop body
chains a f32 scalar through each iteration's output and perturbs the
first input with it, so iterations form a serial data dependency: XLA can
neither hoist the (otherwise loop-invariant) op out of the while loop nor
dead-code-eliminate it.  The added work is one fused elementwise pass
over the first input plus an 8-byte extract — noise for compute-bound
ops; at most one extra memory pass for bandwidth-bound ones, and it lands
on both sides of any A/B comparison equally.

The returned per-iter time is measured by fetching the loop's scalar
result to host (over this link, ``block_until_ready`` can acknowledge
before the chip finishes; a device_get cannot).
"""

from __future__ import annotations

import os
import statistics
import time

# persistent XLA compile cache (see bench.py): kernel A/B sweeps recompile
# dozens of loop programs; over the tunnel each costs minutes without this
os.environ.setdefault(
    'JAX_COMPILATION_CACHE_DIR',
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 '.jax_cache'))
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '2')

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
try:
    # platform shim: makes JAX_PLATFORMS authoritative BEFORE backend
    # discovery — a bare `import jax` can hang for minutes on plugin
    # discovery when the tunnel is half-down, even for CPU-only runs
    import cxxnet_tpu  # noqa: F401
except Exception as _e:  # degraded: the very hang this guards may return
    print(f'chiptime: platform shim unavailable ({_e!r}); '
          f'jax import may hang on plugin discovery', file=sys.stderr)

import jax
import jax.numpy as jnp
import numpy as np


def make_loop(fn, length: int):
    """Jitted fn running ``fn(*args)`` ``length`` times serially on-device,
    returning a f32 scalar data-dependent on every iteration."""

    def run(*args):
        def body(s, _):
            eps = (s * 1e-30).astype(args[0].dtype)
            # perturb ONE element, not all of them: `a + eps` distributes
            # through linear ops — XLA can rewrite dot(a+eps, b) as
            # dot(a,b) + eps*colsum(b), hoist the loop-invariant dot out
            # of the scan, and "measure" above-peak FLOP rates (the r3
            # matmul receipts showed 249 TF/s on a 197-peak chip — the
            # tell).  A scatter-add into [0,...,0] forces a genuine
            # re-execution; its cost is one copy pass over args[0],
            # the same bandwidth the old broadcast-add already paid.
            a0 = args[0].at[(0,) * args[0].ndim].add(eps)
            out = fn(a0, *args[1:])
            # consume EVERY output leaf through a non-factorable reduction:
            # a single-element carry (out[0]) lets XLA push the slice into
            # the op and compute one row of a matmul / one window of an
            # LRN instead of the op ("measuring" negative microseconds),
            # and an unconsumed leaf (e.g. the 2nd grad of a fwd+bwd
            # probe) is dead code.  max|.| cannot be algebraically pushed
            # through dot/conv/reduce_window; its cost is one bandwidth
            # pass per leaf, identical on both sides of an A/B pair.
            for leaf in jax.tree.leaves(out):
                s = s + jnp.max(jnp.abs(leaf)).astype(jnp.float32)
            return s * 0.5, None

        s, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=length)
        return s

    return jax.jit(run)


def grad_probe(fn, nargs: int = None):
    """fwd+bwd probe for A/B kernel comparisons: value_and_grad of
    ``0.5*sum(fn(*args)**2)`` wrt EVERY array argument.

    Two traps this construction avoids: ``grad(sum(fn))`` has a constant
    all-ones cotangent, which XLA algebra can exploit — for a matmul it
    simplifies the backward to a column-sum reduction AND dead-code-
    eliminates the forward (grad-only output) entirely, so the "XLA side"
    of the comparison measures a degenerate program.  Squaring makes the
    cotangent the forward output itself (forward must run, backward gets a
    dense data-dependent cotangent, like a real training step), and
    returning the value keeps the forward live."""

    def probe(*args):
        n = len(args) if nargs is None else nargs

        def loss(*a):
            out = fn(*a)
            return 0.5 * jnp.sum(out.astype(jnp.float32) ** 2)

        val, grads = jax.value_and_grad(
            loss, argnums=tuple(range(n)))(*args)
        return (val,) + tuple(grads)

    return probe


def time_op(fn, args, iters: int = None, reps: int = 5,
            target_s: float = 0.15) -> float:
    """Per-iteration seconds of ``fn(*args)`` on device, dispatch cost
    cancelled via the N-vs-1 difference quotient.

    Each endpoint takes the MIN over ``reps`` runs before the quotient:
    the link cost is a constant floor plus positive jitter spikes (multi-
    ms RTT variance), so min is the right noise rejector — a median
    quotient of noisy single runs can even go negative for sub-ms ops.
    ``iters`` is sized adaptively (from a 50-iter probe) so each timed
    run carries ~``target_s`` seconds of real compute, keeping the signal
    well above the residual link jitter for sub-100us ops."""
    f_1 = make_loop(fn, 1)
    float(np.asarray(f_1(*args)))        # compile + warm
    if iters is None:
        f_probe = make_loop(fn, 50)
        float(np.asarray(f_probe(*args)))
        t = []
        for _ in range(2):
            t0 = time.perf_counter()
            float(np.asarray(f_probe(*args)))
            t.append(time.perf_counter() - t0)
        est = min(t) / 50                # overhead/50 inflates est: fine
        iters = int(min(2000, max(50, target_s / max(est, 1e-7))))
    f_n = make_loop(fn, iters)
    float(np.asarray(f_n(*args)))
    t1s, tns = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(f_1(*args)))
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        float(np.asarray(f_n(*args)))
        tns.append(time.perf_counter() - t0)
    return (min(tns) - min(t1s)) / (iters - 1)


def atomic_receipt_dump(path, payload, partial: bool) -> None:
    """Atomic (tmp + os.replace) JSON receipt write — THE dump helper for
    every receipt-producing tool; keep the contract here, next to the
    timing loop, not copy-pasted per tool.

    ``partial=True`` keeps the receipt re-runnable by the idempotent
    runners (tools/tunnel_lib.sh ``receipt_ok`` treats partial as
    not-landed); call once more with ``partial=False`` only when every
    row is final.  Rewrite after EVERY row: a tunnel wedge mid-suite
    must never cost a finished measurement (the round-4 tile sweep lost
    its JSON exactly this way), and the tmp+replace means a mid-write
    kill can't leave a truncated non-empty unparseable file."""
    import json
    if not path:
        return
    payload = dict(payload)
    if partial:
        payload['partial'] = True
    else:
        payload.pop('partial', None)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)
