"""On-chip A/B of conv lowerings (native fgc vs im2col-GEMM vs per-group
split) at the AlexNet shapes BASELINE.md names as the MFU ceiling-setters:
conv1 (11x11 s4 on a 3-deep input — MXU lane underfill) and the ngroup=2
conv2/4/5 (feature_group_count halves contraction depth per pass).

Timing: chiptime.time_op quotient loops (dispatch-cancelled, scatter-add
perturbation); fwd and fwd+bwd (grad_probe) per lowering.  Receipt feeds
the conv_lowering 'auto' policy (layers/conv.py) — a lowering only
becomes an auto default with a win recorded here.

Usage: python tools/conv_lowering_bench.py [--json receipts/conv_lowering.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from chiptime import atomic_receipt_dump, grad_probe, time_op  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# time the SHIPPED lowerings — the receipt decides conv.py's auto policy,
# so it must measure the code that policy gates, not a copy
from cxxnet_tpu.layers.conv import (conv_im2col, conv_native,  # noqa: E402
                                    conv_s2d, conv_split)

# (name, batch, in_y/x, cin, cout, kernel, stride, pad, ngroup)
SHAPES = [
    ('conv1 b256 227x227x3->96 k11s4', 256, 227, 3, 96, 11, 4, 0, 1),
    ('conv2 b256 27x27x96->256 k5 g2', 256, 27, 96, 256, 5, 1, 2, 2),
    ('conv4 b256 13x13x384->384 k3 g2', 256, 13, 384, 384, 3, 1, 1, 2),
    ('conv5 b256 13x13x384->256 k3 g2', 256, 13, 384, 256, 3, 1, 1, 2),
]


def lowering_fns(k, stride, pad, g):
    strides = (stride, stride)
    padding = ((pad, pad), (pad, pad))
    out = {'native': lambda x, w: conv_native(x, w, strides, padding, g)}
    if g == 1:
        out['im2col'] = lambda x, w: conv_im2col(x, w, strides, padding)
        if stride > 1 and pad % stride == 0:
            out['s2d'] = lambda x, w: conv_s2d(x, w, strides, padding)
    else:
        out['split'] = lambda x, w: conv_split(x, w, strides, padding, g)
    return out


def flops(b, y, cin, cout, k, stride, pad, g):
    o = (y + 2 * pad - k) // stride + 1
    return 2 * b * o * o * (cin // g) * k * k * cout


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--json', default=None)
    ap.add_argument('--only', default=None, help='substring filter on name')
    ap.add_argument('--smoke', action='store_true',
                    help='batch 4 (CPU pipe-clean, numbers meaningless)')
    args = ap.parse_args()
    if args.smoke:
        global SHAPES
        SHAPES = [(n, 4, y, ci, co, k, s, p, g)
                  for (n, _, y, ci, co, k, s, p, g) in SHAPES]

    dev = jax.devices()[0]
    print(f'device: {dev.device_kind} ({dev.platform})', flush=True)
    rng = np.random.RandomState(0)
    results = []
    for (name, b, y, cin, cout, k, stride, pad, g) in SHAPES:
        if args.only and args.only not in name:
            continue
        x = jnp.asarray(rng.randn(b, y, y, cin), jnp.bfloat16)
        w = jnp.asarray(0.01 * rng.randn(k, k, cin // g, cout), jnp.bfloat16)
        fns = lowering_fns(k, stride, pad, g)
        gf = flops(b, y, cin, cout, k, stride, pad, g)
        base = {}
        for passname, wrap in (('fwd', lambda f: f), ('fwd+bwd', grad_probe)):
            mult = 1 if passname == 'fwd' else 3   # bwd ~2x fwd FLOPs
            for lname, fn in fns.items():
                t = time_op(wrap(fn), (x, w))
                tf = gf * mult / t / 1e12
                r = {'op': name, 'pass': passname, 'lowering': lname,
                     'us': round(t * 1e6, 1), 'tflops': round(tf, 1)}
                if lname == 'native':
                    base[passname] = t
                elif base.get(passname):
                    r['speedup_vs_native'] = round(base[passname] / t, 3)
                results.append(r)
                extra = ('  %.3fx vs native' % (base[passname] / t)
                         if lname != 'native' and base.get(passname) else '')
                print(f'{name:34s} {passname:7s} {lname:7s} '
                      f'{t * 1e6:9.1f}us  {tf:6.1f} TF/s{extra}',
                      flush=True)
                # durability: dump partial results as each row lands;
                # atomic replace so a mid-write kill can't leave a
                # truncated (non-empty but unparseable) receipt.  The
                # 'partial' flag comes off only in the final dump below,
                # so an idempotent relaunch (run_chip_pending.sh) re-runs
                # an interrupted sweep instead of skipping it forever.
                if args.json:
                    _dump_json(args.json, dev, results, partial=True)
    if args.json and results:
        _dump_json(args.json, dev, results, partial=False)
        print(f'wrote {args.json}')
    elif args.json:
        print(f'NOTHING matched --only={args.only}: {args.json} NOT written')
    return 0


def _dump_json(path, dev, results, partial):
    atomic_receipt_dump(path, {'device': dev.device_kind,
                               'dtype': 'bfloat16', 'results': results},
                        partial)


if __name__ == '__main__':
    raise SystemExit(main())
