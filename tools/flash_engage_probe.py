#!/usr/bin/env python
"""Validate the flash-attention auto-engage gate against reality.

    python tools/flash_engage_probe.py [--json out.json]

The ``attn_use_flash`` gate (ops/pallas_kernels.py) is a MEMORY
feasibility bound: dense attention materializes a b*h*s^2 f32 score
matrix, so past ~4 GiB the Pallas flash kernel is the only way to run
the shape at all.  Every SPEED-measured shape fit in HBM (dense won,
receipts/micro_attn.json) — so until this probe, the gate's engage side
had never been exercised on the real chip.  Three facts land in the
receipt:

1. at a dense-INFEASIBLE length (b1 h8 s32768: 34 GiB of scores) the
   gate engages and the flash forward completes with finite output;
2. its on-device time (K-vs-1 quotient, tools/chiptime.py);
3. at a dense-feasible length the same kernel matches the dense
   reference numerically (the correctness half, checkable only where
   dense fits).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault(
    'JAX_COMPILATION_CACHE_DIR',
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 '.jax_cache'))
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '2')

from chiptime import atomic_receipt_dump, time_op              # noqa: E402

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument('--json', default=None)
    ap.add_argument('--seq', type=int, default=32768)
    ap.add_argument('--heads', type=int, default=8)
    ap.add_argument('--dim', type=int, default=64)
    args = ap.parse_args()

    from cxxnet_tpu.ops.pallas_kernels import (attn_use_flash,
                                               flash_attention)
    from cxxnet_tpu.parallel.sequence import attention_reference

    payload = {'metric': 'flash_engage_probe', 'seq': args.seq,
               'heads': args.heads, 'head_dim': args.dim, 'value': None}

    def dump(partial=True):
        atomic_receipt_dump(args.json, payload, partial)

    # 1. the gate must engage at the dense-infeasible shape and stay off
    #    at the measured dense-feasible ones
    engaged = attn_use_flash(args.seq, batch=1, heads=args.heads)
    payload['gate_engages_at_infeasible'] = bool(engaged)
    payload['gate_off_at_4096'] = not attn_use_flash(4096, batch=2, heads=8)
    dump()
    if not engaged:
        payload['error'] = ('attn_use_flash did not engage at the '
                            'dense-infeasible length — gate broken or '
                            'not on a real TPU')
        dump(partial=False)
        print(json.dumps(payload))
        return 1

    # 2. correctness where dense still fits (bf16 tolerance)
    rng = jax.random.PRNGKey(0)
    small = 2048
    qs, ks, vs = (jax.random.normal(jax.random.fold_in(rng, i),
                                    (1, small, args.heads, args.dim),
                                    jnp.bfloat16) for i in range(3))
    ref = attention_reference(qs, ks, vs, causal=True)
    got = flash_attention(qs, ks, vs, causal=True)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    payload['small_check_max_abs_err'] = round(err, 5)
    payload['small_check_ok'] = err < 0.05
    dump()

    # 3. the engaged forward at the infeasible length: completes, finite,
    #    timed
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, 10 + i),
                                 (1, args.seq, args.heads, args.dim),
                                 jnp.bfloat16) for i in range(3))

    def fwd(q, k, v):
        return flash_attention(q, k, v, causal=True)

    out = jax.jit(fwd)(q, k, v)
    finite = bool(np.isfinite(
        float(jnp.sum(out.astype(jnp.float32)))))
    payload['infeasible_fwd_finite'] = finite
    dump()
    t = time_op(fwd, (q, k, v), iters=5, reps=3)
    payload['infeasible_fwd_ms'] = round(t * 1e3, 2)
    payload['value'] = round(t * 1e3, 2)
    payload['unit'] = 'ms (b1 h8 s%d causal flash fwd)' % args.seq
    ok = (finite and payload['small_check_ok']
          and payload['gate_off_at_4096'])
    if not ok:
        # a failed validation must never pass receipt_ok as a landed
        # measurement: mark it so the idempotent runner re-runs the step
        payload['error'] = 'probe checks failed: ' + ', '.join(
            k for k, v in (('finite', finite),
                           ('small_check_ok', payload['small_check_ok']),
                           ('gate_off_at_4096',
                            payload['gate_off_at_4096'])) if not v)
    dump(partial=False)
    print(json.dumps(payload))
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
