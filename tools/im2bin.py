#!/usr/bin/env python
"""im2bin — pack images listed in a .lst file into a BinaryPage stream.

Equivalent of the reference packer (``/root/reference/tools/im2bin.cpp``):
each image file's raw encoded bytes become one object in a sequence of
64MB pages; records follow .lst order so the imgbin iterator can pair them.

Usage: python tools/im2bin.py image.lst image_root out.bin
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from cxxnet_tpu.io.iter_img import parse_lst_line
from cxxnet_tpu.utils.io_stream import BinaryPage


def main(argv):
    if len(argv) != 3:
        print(__doc__)
        return 1
    lst_path, root, out_path = argv
    page = BinaryPage()
    n = 0
    with open(out_path, 'wb') as fo, open(lst_path) as fl:
        for line in fl:
            if not line.strip():
                continue
            _, _, fname = parse_lst_line(line)
            with open(os.path.join(root, fname) if root != '.' else fname,
                      'rb') as fi:
                blob = fi.read()
            if not page.push(blob):
                page.save(fo)
                page.clear()
                if not page.push(blob):
                    raise ValueError(f'image larger than a page: {fname}')
            n += 1
            if n % 1000 == 0:
                print(f'{n} images packed')
        if page.size:
            page.save(fo)
    print(f'packed {n} images into {out_path}')
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
