#!/usr/bin/env python3
"""Partitioned imgbin dataset packer.

Port of ``/root/reference/tools/imgbin-partition-maker.py``: splits a big
``.lst`` into size-bounded partitions named ``(prefix % i)`` and emits a
Makefile whose rules pack each partition with im2bin — the multi-part
layout consumed by ``image_conf_prefix`` / ``image_conf_ids``
(``iter_thread_imbin-inl.hpp:225-278``).  ``--pack`` additionally runs the
in-tree packer directly so no ``make`` step is needed.

Example::

    python tools/imgbin_partition_maker.py --img_list train.lst \\
        --img_root ./images/ --prefix part%02d --out ./parts \\
        --partition_size 256 --shuffle 1 --pack

Then in the conf::

    image_conf_prefix = ./parts/part%02d
    image_conf_ids = 1-8
"""

from __future__ import annotations

import argparse
import os
import random
import shlex
import subprocess
import sys


def split_partitions(lines, img_root, part_bytes):
    """Greedy split: a new partition starts when adding the next image
    would exceed the size budget (file bytes + BinaryPage header growth,
    like the reference's ``sz + 10240`` guard)."""
    parts, cur, sz = [], [], 0
    for item in lines:
        path = item.rstrip('\n').split('\t')[2]
        fsz = os.path.getsize(os.path.join(img_root, path))
        if cur and sz + fsz + 10240 > part_bytes:
            parts.append(cur)
            cur, sz = [], 0
        cur.append(item)
        sz += fsz + (len(cur) + 2) * 4
    if cur:
        parts.append(cur)
    return parts


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Generate partitioned .lst files + a Makefile (or pack '
                    'directly with --pack) for multi-part imgbin datasets')
    ap.add_argument('--img_list', required=True,
                    help='path to the list of all images')
    ap.add_argument('--img_root', required=True,
                    help='prefix path of the file paths in img_list')
    ap.add_argument('--im2bin', default=' '.join(shlex.quote(p) for p in (
        sys.executable, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), 'im2bin.py'))),
        help='im2bin command for the generated Makefile rules '
             '(shell-quoted)')
    ap.add_argument('--partition_size', default='256',
                    help='max size of a single bin file, MB')
    ap.add_argument('--shuffle', default='0',
                    help='shuffle the list before splitting (1/0)')
    ap.add_argument('--prefix', required=True,
                    help='printf-style partition name, e.g. part%%02d')
    ap.add_argument('--out', required=True,
                    help='output folder for the partition lists/bins')
    ap.add_argument('--makefile', default='Gen.mk',
                    help='name of the generated Makefile')
    ap.add_argument('--pack', action='store_true',
                    help='run im2bin on every partition now instead of '
                         'only emitting the Makefile')
    ap.add_argument('--seed', type=int, default=888)
    args = ap.parse_args(argv)

    with open(args.img_list) as f:
        lines = f.readlines()
    if args.shuffle == '1':
        random.Random(args.seed).shuffle(lines)

    os.makedirs(args.out, exist_ok=True)
    parts = split_partitions(lines, args.img_root,
                             int(args.partition_size) << 20)
    rules, bins = [], []
    for i, part in enumerate(parts, start=1):
        stem = os.path.join(args.out, args.prefix % i)
        with open(stem + '.lst', 'w') as fw:
            fw.writelines(part)
        bins.append(stem + '.bin')
        q = shlex.quote
        rules.append(f'{stem}.bin: {stem}.lst\n\t{args.im2bin} '
                     f'{q(stem + ".lst")} {q(args.img_root)} '
                     f'{q(stem + ".bin")}')
    with open(args.makefile, 'w') as fo:
        fo.write('all: ' + ' '.join(bins) + '\n\n')
        fo.write('\n\n'.join(rules) + '\n')
    print(f'{len(parts)} partition list(s) under {args.out}; '
          f'Makefile: {args.makefile}')
    print(f'conf: image_conf_prefix = {os.path.join(args.out, args.prefix)}')
    print(f'      image_conf_ids = 1-{len(parts)}')

    if args.pack:
        for b in bins:
            stem = b[:-4]
            subprocess.check_call(shlex.split(args.im2bin) +
                                  [stem + '.lst', args.img_root, b])
    return 0


if __name__ == '__main__':
    sys.exit(main())
