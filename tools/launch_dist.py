#!/usr/bin/env python
"""Local multi-worker launcher — analog of the reference's ps-lite tracker
(reference ``example/MNIST/mpi.conf`` + dmlc launcher).

Reads a launcher config (``num_workers``, ``app_conf``, ``coordinator``,
``arg``) and spawns one trainer process per worker with the environment
contract consumed by ``cxxnet_tpu.parallel.distributed``:

  CXXNET_COORDINATOR   coordinator host:port (worker 0 binds it)
  CXXNET_NUM_WORKER    number of processes in the job
  PS_RANK              this process's rank (reference env var name kept,
                       iter_thread_imbin-inl.hpp:190-194 — also shards the
                       data pipeline per worker)

For a real TPU pod each host runs the same command under its own scheduler
(GKE/xmanager); this script is the single-machine version for development
and CI, forcing each worker onto the CPU backend.
"""

from __future__ import annotations

import os
import subprocess
import sys


def parse_launcher_conf(path):
    cfg = {}
    with open(path) as f:
        for line in f:
            line = line.split('#', 1)[0].strip()
            if not line or '=' not in line:
                continue
            k, _, v = line.partition('=')
            cfg[k.strip()] = v.strip()
    return cfg


def main(argv):
    if not argv:
        print('Usage: launch_dist.py <launcher.conf> [extra k=v ...]')
        return 1
    conf_path = argv[0]
    cfg = parse_launcher_conf(conf_path)
    nworker = int(cfg.get('num_workers', '1'))
    app_conf = cfg.get('app_conf')
    if not app_conf:
        print(f'{conf_path}: missing required key "app_conf" '
              '(the trainer config each worker runs)')
        return 1
    coord = cfg.get('coordinator', '127.0.0.1:9900')
    extra = cfg.get('arg', '').split() + list(argv[1:])
    workdir = os.path.dirname(os.path.abspath(conf_path))
    procs = []
    for rank in range(nworker):
        env = dict(os.environ)
        env.update({
            'CXXNET_COORDINATOR': coord,
            'CXXNET_NUM_WORKER': str(nworker),
            'PS_RANK': str(rank),
            'JAX_PLATFORMS': 'cpu',
        })
        cmd = [sys.executable, '-m', 'cxxnet_tpu.main', app_conf] + extra + [
            f'dist_num_worker={nworker}', f'dist_worker_rank={rank}']
        procs.append(subprocess.Popen(cmd, cwd=workdir, env=env))
    rcs = [p.wait() for p in procs]
    return next((rc for rc in rcs if rc), 0)


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
