#!/usr/bin/env python
"""graftlint CLI — drive the project's static invariant checkers.

Usage::

    python tools/lint.py [--rule RULE ...] [--baseline PATH | --no-baseline]
                         [--list-rules] [--update-baseline] [root]

Exit codes (doc/static_analysis.md):

* ``0`` — clean: no findings, or every finding matches a baseline
  entry exactly.
* ``1`` — the lint contract is violated: NEW findings (fix, allow with
  a reason, or — exceptionally — baseline with a reason), or STALE
  baseline entries (a fixed finding must also delete its entry: the
  baseline only shrinks).
* ``2`` — internal error (checker crash, unreadable baseline): the
  lint could not render a verdict, treat as infrastructure failure.

``--update-baseline`` enforces the shrink-only policy mechanically: it
rewrites the baseline keeping only still-live entries (reasons
preserved) and refuses to add anything — new findings still exit 1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cxxnet_tpu.analysis import core  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument('root', nargs='?', default=None,
                   help='repository root (default: this checkout)')
    p.add_argument('--rule', action='append', default=None,
                   help='run only this rule (repeatable)')
    p.add_argument('--baseline', default=None,
                   help='baseline json (default: <root>/lint_baseline.json)')
    p.add_argument('--no-baseline', action='store_true',
                   help='ignore the baseline: every finding is new')
    p.add_argument('--update-baseline', action='store_true',
                   help='drop stale entries from the baseline (shrink-only; '
                        'never adds)')
    p.add_argument('--list-rules', action='store_true')
    p.add_argument('-q', '--quiet', action='store_true')
    args = p.parse_args(argv)

    if args.list_rules:
        for r in core.ALL_RULES:
            print(r)
        return 0

    try:
        root = os.path.abspath(args.root) if args.root else core.default_root()
        findings = core.run_all(root=root, rules=args.rule)
        if args.no_baseline:
            entries = []
            bl_path = None
        else:
            bl_path = args.baseline or core.baseline_path(root)
            entries = core.load_baseline(bl_path)
        new, stale, matched = core.diff_against_baseline(findings, entries)
    except Exception:
        traceback.print_exc()
        print('lint: internal error (no verdict)', file=sys.stderr)
        return 2

    for f in new:
        print(f.format())
    for e in stale:
        print(f'stale baseline entry (finding fixed — delete it): '
              f'[{e["rule"]}] {e["path"]}: {e["message"]}')

    if args.update_baseline and stale and bl_path:
        # remove ONE occurrence per stale entry: identical duplicate
        # entries are legitimate (multiset matching), and only the
        # unmatched copies are stale
        live = list(entries)
        for e in stale:
            live.remove(e)
        with open(bl_path, 'w', encoding='utf-8') as f:
            json.dump({'policy': 'shrink-only', 'entries': live}, f,
                      indent=2, sort_keys=True)
            f.write('\n')
        print(f'lint: baseline shrunk {len(entries)} -> {len(live)} '
              f'({bl_path})')
        stale = []

    if not args.quiet:
        print(f'lint: {len(findings)} finding(s), {matched} baselined, '
              f'{len(new)} new, {len(stale)} stale', file=sys.stderr)
    return 1 if (new or stale) else 0


if __name__ == '__main__':
    sys.exit(main())
