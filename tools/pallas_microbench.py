#!/usr/bin/env python
"""Per-op microbenchmark: Pallas kernels vs their XLA lowerings on the
real chip, at the shapes the framework actually runs (AlexNet LRN/fullc,
transformer attention).

    python tools/pallas_microbench.py [--json out.json]

Each op is timed fwd-only and fwd+bwd (grad through the op), looped
on-device inside one jit with the dispatch cost cancelled (see
chiptime.py — per-dispatch timing bottoms out at the ~7 ms tunnel RTT and
cannot rank kernels).  Results feed BASELINE.md's kernel table and decide
the default `use_pallas` state (ops/pallas_kernels.py: pallas wins ->
enabled by default).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(            # persistent XLA cache — see chiptime.py
    'JAX_COMPILATION_CACHE_DIR',
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 '.jax_cache'))
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '2')

# chiptime FIRST: its preamble imports the cxxnet_tpu platform shim
# before jax, so CPU-mode runs can't hang on plugin discovery during
# tunnel outages
from chiptime import atomic_receipt_dump, grad_probe, time_op  # noqa: E402

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402


_PASS_WRAPS = {'fwd': lambda f: f, 'fwd+bwd': None, 'bwd-op': lambda f: f}


def bench_pair(name, xla_fn, pallas_fn, args, results, flops=None,
               passes=('fwd', 'fwd+bwd')):
    # loop length is adaptive (chiptime.time_op auto-sizes iterations).
    # 'bwd-op' times a raw backward building block as-is (no grad wrap —
    # the raw impls aren't differentiable themselves).
    for tag in passes:
        wrap = _PASS_WRAPS[tag] or grad_probe
        t_x = time_op(wrap(xla_fn), args)
        t_p = time_op(wrap(pallas_fn), args)
        speedup = t_x / max(t_p, 1e-9)
        row = {'op': name, 'pass': tag,
               'xla_us': round(t_x * 1e6, 1),
               'pallas_us': round(t_p * 1e6, 1),
               'pallas_speedup': round(speedup, 3)}
        note = ''
        if flops is not None:
            # physically-impossible sanity column: >peak means the timing
            # (or a compiler simplification) is lying
            fl = flops * (3.0 if tag == 'fwd+bwd' else 1.0)
            row['xla_tflops'] = round(fl / max(t_x, 1e-9) / 1e12, 1)
            row['pallas_tflops'] = round(fl / max(t_p, 1e-9) / 1e12, 1)
            note = (f"  [{row['xla_tflops']:6.1f} vs "
                    f"{row['pallas_tflops']:6.1f} TF/s]")
        results.append(row)
        print(f'{name:28s} {tag:8s} xla {t_x * 1e6:9.1f}us  '
              f'pallas {t_p * 1e6:9.1f}us  speedup {speedup:6.3f}x{note}',
              flush=True)


def lrn_xla(x, nsize, alpha, beta, knorm):
    """The layer's default XLA path (layers/norm.py math)."""
    sq = (x * x).astype(jnp.float32)
    half_lo = (nsize - 1) // 2
    half_hi = nsize - 1 - half_lo
    win = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add, (1, 1, 1, nsize), (1, 1, 1, 1),
        [(0, 0), (0, 0), (0, 0), (half_lo, half_hi)])
    norm = knorm + (alpha / nsize) * win
    return (x.astype(jnp.float32) * norm ** (-beta)).astype(x.dtype)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument('--json', default=None)
    ap.add_argument('--dtype', default='bfloat16',
                    choices=['bfloat16', 'float32'])
    ap.add_argument('--only', default='',
                    help='comma list of op groups: lrn,matmul,attn,'
                         'matmul_bwd,matmul_tiles')
    args = ap.parse_args()
    only = set(args.only.split(',')) if args.only else None

    def want(group):
        return only is None or group in only

    from cxxnet_tpu.ops.pallas_kernels import (flash_attention, lrn_pallas,
                                               pallas_matmul)
    from cxxnet_tpu.parallel.sequence import attention_reference

    dev = jax.devices()[0]
    print(f'device: {dev.device_kind} ({dev.platform})', flush=True)
    dtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32
    rng = np.random.RandomState(0)

    def dump(rows, partial: bool) -> None:
        atomic_receipt_dump(args.json,
                            {'device': dev.device_kind,
                             'dtype': args.dtype, 'results': list(rows)},
                            partial)

    class _DumpingList(list):
        def append(self, row):
            super().append(row)
            dump(self, partial=True)

    results = _DumpingList()

    # --- LRN at AlexNet shapes (NHWC) ---------------------------------
    for b, h, w, c in (((256, 27, 27, 96), (256, 13, 13, 256))
                       if want('lrn') else ()):
        x = jnp.asarray(rng.randn(b, h, w, c), dtype)
        bench_pair(f'lrn {b}x{h}x{w}x{c}',
                   functools.partial(lrn_xla, nsize=5, alpha=1e-4,
                                     beta=0.75, knorm=1.0),
                   lambda y: lrn_pallas(y, 5, 1e-4, 0.75, 1.0),
                   (x,), results)

    # --- fullc matmuls at AlexNet shapes ------------------------------
    for m, k, n in (((256, 9216, 4096), (256, 4096, 4096),
                     (256, 4096, 1000)) if want('matmul') else ()):
        a = jnp.asarray(rng.randn(m, k) * 0.05, dtype)
        bmat = jnp.asarray(rng.randn(k, n) * 0.05, dtype)
        bench_pair(f'matmul {m}x{k}x{n}',
                   lambda p, q: jnp.dot(p, q), pallas_matmul,
                   (a, bmat), results, flops=2.0 * m * k * n)

    # --- backward-matmul kernels (da = g@b^T, db = a^T@g) -------------
    # A/Bs the dedicated transpose-free NT/TN kernels against XLA's own
    # contraction of the stored layouts — the r3 fwd+bwd ratio (0.33x)
    # bundled a physical 75MB weight transpose into the pallas side
    if only is not None and 'matmul_bwd' in only:   # opt-in, like tiles
        from cxxnet_tpu.ops.pallas_kernels import (_matmul_nt_impl,
                                                   _matmul_tn_impl)
        for m, k, n in ((256, 9216, 4096), (256, 4096, 4096)):
            g = jnp.asarray(rng.randn(m, n) * 0.05, dtype)
            a = jnp.asarray(rng.randn(m, k) * 0.05, dtype)
            bmat = jnp.asarray(rng.randn(k, n) * 0.05, dtype)
            fl = 2.0 * m * k * n
            bench_pair(f'da=g@bT {m}x{k}x{n}',
                       lambda p, q: jax.lax.dot_general(
                           p, q, (((1,), (1,)), ((), ()))),
                       _matmul_nt_impl, (g, bmat), results, flops=fl,
                       passes=('bwd-op',))
            bench_pair(f'db=aT@g {m}x{k}x{n}',
                       lambda p, q: jax.lax.dot_general(
                           p, q, (((0,), (0,)), ((), ()))),
                       _matmul_tn_impl, (a, g), results, flops=fl,
                       passes=('bwd-op',))

    # --- matmul tile-size sweep (kernel tuning, fwd only) -------------
    # answers "is the 45% matmul gap a tiling problem?" in one run:
    # every (tm, tn, tk) variant of the K-blocked kernel vs XLA's dot
    # at the two big fullc shapes.  Opt-in only (--only matmul_tiles):
    # ~16 fresh kernel compiles would bloat the standard receipt run.
    if only is not None and 'matmul_tiles' in only:
        from cxxnet_tpu.ops.pallas_kernels import _matmul_impl
        for m, k, n in ((256, 9216, 4096), (256, 4096, 4096)):
            a = jnp.asarray(rng.randn(m, k) * 0.05, dtype)
            bmat = jnp.asarray(rng.randn(k, n) * 0.05, dtype)
            t_x = time_op(lambda p, q: jnp.dot(p, q), (a, bmat))
            fl = 2.0 * m * k * n
            print(f'matmul {m}x{k}x{n} XLA {t_x * 1e6:9.1f}us '
                  f'[{fl / t_x / 1e12:6.1f} TF/s]', flush=True)
            results.append({'op': f'matmul {m}x{k}x{n}', 'pass': 'fwd',
                            'tiles': 'xla', 'us': round(t_x * 1e6, 1),
                            'tflops': round(fl / t_x / 1e12, 1)})
            for tm, tn, tk in ((256, 256, 512), (128, 256, 512),
                               (256, 512, 512), (512, 512, 512),
                               (256, 256, 1024), (128, 512, 1024),
                               (256, 1024, 512), (512, 256, 1024)):
                f = functools.partial(_matmul_impl, tile_m=tm, tile_n=tn,
                                      tile_k=tk)
                try:
                    t_p = time_op(f, (a, bmat))
                except Exception as e:   # VMEM OOM at big tiles: record
                    print(f'  tiles {tm}x{tn}x{tk}: FAILED '
                          f'{type(e).__name__}', flush=True)
                    results.append({'op': f'matmul {m}x{k}x{n}',
                                    'pass': 'fwd',
                                    'tiles': f'{tm}x{tn}x{tk}',
                                    'error': type(e).__name__})
                    continue
                print(f'  tiles {tm}x{tn}x{tk}: {t_p * 1e6:9.1f}us '
                      f'[{fl / t_p / 1e12:6.1f} TF/s] '
                      f'{t_x / t_p:5.3f}x of XLA', flush=True)
                results.append({'op': f'matmul {m}x{k}x{n}',
                                'pass': 'fwd', 'tiles': f'{tm}x{tn}x{tk}',
                                'us': round(t_p * 1e6, 1),
                                'tflops': round(fl / t_p / 1e12, 1),
                                'vs_xla': round(t_x / t_p, 3)})

    # --- attention at transformer shapes ------------------------------
    for b, s, heads, d in (((4, 1024, 8, 64), (2, 4096, 8, 64))
                           if want('attn') else ()):
        q = jnp.asarray(rng.randn(b, s, heads, d) * 0.1, dtype)
        k = jnp.asarray(rng.randn(b, s, heads, d) * 0.1, dtype)
        v = jnp.asarray(rng.randn(b, s, heads, d) * 0.1, dtype)
        for causal in (False, True):
            bench_pair(
                f'attn b{b} s{s} h{heads} d{d}'
                f'{" causal" if causal else ""}',
                functools.partial(attention_reference, causal=causal),
                functools.partial(flash_attention, causal=causal),
                (q, k, v), results)

    dump(results, partial=False)
    if args.json:
        print(f'wrote {args.json}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
