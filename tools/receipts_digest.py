"""One-screen digest of every receipt under receipts/ — the quick answer
to "what is measured, what is pending, what is suspect".

    python tools/receipts_digest.py [--dir receipts]

Flags surfaced per receipt: partial (interrupted run), superseded
(marked for re-measure, with reason), error.  Bench receipts print their
headline metric; micro/breakdown receipts print row counts and the
best/worst speedup.
"""

import argparse
import json
import os


def describe(path):
    name = os.path.basename(path)
    try:
        d = json.load(open(path))
    except Exception as e:
        return f'{name:34s} UNPARSEABLE ({type(e).__name__})'
    flags = []
    if d.get('error') is not None:
        flags.append(f'ERROR: {d["error"]}')
    if d.get('partial'):
        flags.append('PARTIAL')
    if d.get('superseded'):
        why = str(d['superseded'])
        flags.append('SUPERSEDED: '
                     + (why[:60] + '...' if len(why) > 60 else why))
    flag = ('  [' + '; '.join(flags) + ']') if flags else ''

    if 'value' in d:                      # bench.py schema
        unit = d.get('unit') or ''
        extra = ''
        for k in ('mfu', 'step_ms', 'host_link_mb_per_s',
                  'uint8_wire_images_per_sec'):
            if d.get(k) is not None:
                extra += f'  {k}={d[k]}'
        return f'{name:34s} {d.get("value")} {unit}{extra}{flag}'
    if 'results' in d:                    # micro/conv-lowering schema
        rows = d['results']
        bad = sum(1 for r in rows if r.get('error') is not None)
        sp = [next((r[k] for k in
                    ('pallas_speedup', 'speedup_vs_native', 'vs_xla')
                    if r.get(k) is not None), None) for r in rows]
        sp = [s for s in sp if s is not None]
        rng = (f'  speedup {min(sp):.2f}x..{max(sp):.2f}x' if sp else '')
        err = f'  ({bad} ERROR rows)' if bad else ''
        return f'{name:34s} {len(rows)} rows{err}{rng}{flag}'
    if 'layers' in d:                     # breakdown schema
        top = sorted(d['layers'], key=lambda r: -r.get('fwd_bwd_us', 0))[:3]
        tops = ', '.join(f'{r["layer"]}={r["fwd_bwd_us"]}us' for r in top)
        step = d.get('step_ms')
        return (f'{name:34s} {len(d["layers"])} layers'
                f'{f"  step={step}ms" if step else ""}'
                f'{"  top: " + tops if tops else ""}{flag}')
    return f'{name:34s} (unrecognized schema){flag}'


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--dir', default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'receipts'))
    args = ap.parse_args()
    names = sorted(n for n in os.listdir(args.dir) if n.endswith('.json'))
    for n in names:
        print(describe(os.path.join(args.dir, n)))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
