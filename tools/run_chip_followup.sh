#!/bin/sh
# Follow-up chip measurements queued behind run_chip_suite.sh: waits for
# the suite to release the chip, then lands the rows the suite doesn't
# carry — the mnist_tta refresh (BASELINE.md promises its receipt) and an
# AlexNet rerun capturing the lrn_auto_mode gate change (full-Pallas LRN
# at norm2 + hybrid at norm1) that was committed after the suite's
# alexnet step ran.  Same durability contract: every receipt commits the
# moment it exists.
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
cd "$REPO" || exit 1

while pgrep -f run_chip_suite.sh >/dev/null 2>&1; do
    sleep 60
done

save() {
    for p in "$@"; do
        [ -e "$p" ] && git add "$p"
    done
    if ! git diff --cached --quiet -- "$@"; then
        git commit -q -m "receipts: $(basename "$1" .json)" -- "$@" ||
            echo "WARNING: receipts NOT committed: $*" >&2
    fi
}

bench() {
    f="$OUT/$2"
    timeout 2700 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save "$f" "$OUT/$2.log"
}

bench mnist_tta bench_mnist_tta.json
bench alexnet   bench_alexnet_lrngate.json
echo "followup done"
