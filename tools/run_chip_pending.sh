#!/bin/bash
# Idempotent chip runner: walks the receipt manifest and runs ONLY the
# steps whose receipt is missing, unparseable, partial, or error-marked.
# Safe to relaunch any number of times (e.g. as a round's first action
# after a restart killed the previous watcher — the exact round-3/4
# failure mode).  Per-step tunnel gate; receipts committed as they land.
#
#   nohup bash tools/run_chip_pending.sh &
#
# Wall-clock-sensitive steps (mnist_tta, e2e) run first: keep the single
# host core idle until their receipts exist.
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

# receipt_ok <file> — 0 when the receipt exists, parses, and is neither
# partial nor error-marked (a null value also counts as failed)
receipt_ok() {
    python - "$1" <<'EOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    raise SystemExit(1)
bad = (d.get('error') is not None or d.get('partial')
       or d.get('superseded')          # marked for re-measure (e.g.
                                       # contended host, suspect baseline)
       or ('value' in d and d['value'] is None))
raise SystemExit(1 if bad else 0)
EOF
}

run_bench() {    # $1 mode, $2 receipt basename — bench.py JSON-on-stdout
    f="$OUT/$2"
    if receipt_ok "$f"; then echo "skip $2 (receipt ok)"; return; fi
    wait_tunnel "$OUT/pending.marker"
    timeout 2700 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save_receipts "$f" "$OUT/$2.log"
}

run_tool() {     # $1 receipt basename, $2... command — tools with --json
    f="$OUT/$1.json"
    log="$OUT/$1.log"
    shift
    if receipt_ok "$f"; then echo "skip $(basename "$f") (receipt ok)"; return; fi
    wait_tunnel "$OUT/pending.marker"
    timeout 2700 "$@" --json "$f" > "$log" 2>&1
    save_receipts "$f" "$log"
}

echo "=== WALL-CLOCK-SENSITIVE (keep host idle) ==="
run_bench mnist_tta    bench_mnist_tta.json
run_bench e2e_alexnet  bench_e2e_devnorm.json
echo "=== ON-DEVICE-TIMED ==="
run_tool micro_matmul_bwd    python tools/pallas_microbench.py --only matmul_bwd
run_tool alexnet_breakdown   python tools/alexnet_breakdown.py
run_tool googlenet_breakdown python tools/alexnet_breakdown.py --model googlenet
run_tool micro_matmul_tiles  python tools/pallas_microbench.py --only matmul_tiles
run_bench transformer  bench_transformer.json
run_tool conv_lowering python tools/conv_lowering_bench.py
echo "pending suite done"
