#!/bin/bash
# Idempotent chip runner: walks the receipt manifest and runs ONLY the
# steps whose receipt is missing, unparseable, partial, or error-marked.
# Safe to relaunch any number of times (e.g. as a round's first action
# after a restart killed the previous watcher — the exact round-3/4
# failure mode).  Per-step tunnel gate; receipts committed as they land.
# Helpers (receipt_ok / run_bench_receipt / run_tool_receipt) live in
# tools/tunnel_lib.sh — the shared home for the receipt-validity
# contract.
#
#   nohup bash tools/run_chip_pending.sh &
#
# Order = priority under a short tunnel window: wall-clock-sensitive
# steps first (they need the single host core idle), then the
# VERDICT-critical never-measured transformer number, then attribution
# and A/Bs.
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

echo "=== WALL-CLOCK-SENSITIVE (keep host idle) ==="
run_bench_receipt mnist_tta    bench_mnist_tta.json
run_bench_receipt e2e_alexnet  bench_e2e_devnorm.json
echo "=== ON-DEVICE-TIMED ==="
run_bench_receipt transformer  bench_transformer.json
if ! receipt_ok "$OUT/bench_transformer.json"; then
    # OOM guard: the b16 x s1024 config's (16,1024,32768) f32 logits are
    # the biggest single tensor any bench allocates — if the full-size
    # run died, land a half-batch receipt rather than nothing
    echo "transformer bench failed at batch 16 — retrying at batch 8"
    (export CXXNET_BENCH_BATCH=8
     run_bench_receipt transformer bench_transformer.json)
fi
run_tool_receipt alexnet_breakdown   python tools/alexnet_breakdown.py
run_tool_receipt googlenet_breakdown python tools/alexnet_breakdown.py --model googlenet
run_tool_receipt micro_matmul_bwd    python tools/pallas_microbench.py --only matmul_bwd
run_tool_receipt micro_matmul_tiles  python tools/pallas_microbench.py --only matmul_tiles
run_tool_receipt conv_lowering python tools/conv_lowering_bench.py
echo "pending suite done"
