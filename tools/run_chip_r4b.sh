#!/bin/bash
# Round-4 chip runner, second wave.  Differences from run_chip_remaining.sh
# learned the hard way on this harness:
#   * the tunnel gate runs BEFORE EVERY STEP, not once at launch — the
#     axon tunnel drops for hours mid-suite, and a step launched into a
#     dead tunnel hangs its whole timeout and produces nothing;
#   * the probe lives in tools/tunnel_lib.sh (shared, bash-only /dev/tcp);
#   * wall-clock-sensitive steps (mnist_tta time-to-accuracy, e2e link
#     measurement) run FIRST and are marked in the driver log so the
#     operator can keep the single host core idle during them; on-device
#     quotient-timed steps follow (host contention cannot skew those);
#   * every receipt is git-added and committed the moment it exists.
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

save() {
    for p in "$@"; do
        [ -e "$p" ] && git add "$p"
    done
    if ! git diff --cached --quiet -- "$@"; then
        git commit -q -m "receipts: $(basename "$1" .json)" -- "$@" ||
            echo "WARNING: receipts NOT committed: $*" >&2
    fi
}

bench() {
    wait_tunnel "$OUT/r4b.marker"
    f="$OUT/$2"
    env $3 timeout 2700 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save "$f" "$OUT/$2.log"
}

micro() {
    wait_tunnel "$OUT/r4b.marker"
    f="$OUT/micro_$1.json"
    timeout 2400 python tools/pallas_microbench.py --only "$1" \
        --json "$f" > "$OUT/micro_$1.log" 2>&1
    save "$f" "$OUT/micro_$1.log"
}

breakdown() {    # $1 = model flag ('' = alexnet), $2 = receipt basename
    wait_tunnel "$OUT/r4b.marker"
    timeout 2700 python tools/alexnet_breakdown.py $1 \
        --json "$OUT/$2.json" > "$OUT/$2.log" 2>&1
    save "$OUT/$2.json" "$OUT/$2.log"
}

echo "=== WALL-CLOCK-SENSITIVE PHASE (keep host idle) ==="
bench mnist_tta    bench_mnist_tta.json
# e2e with the new uint8-wire path (default); separate receipt so the
# committed host-normalize number (bench_e2e.json, 40.1 img/s) stays as
# the A-side of the comparison
bench e2e_alexnet  bench_e2e_devnorm.json
echo "=== ON-DEVICE-TIMED PHASE (host work ok) ==="
micro matmul_bwd
breakdown ""                   alexnet_breakdown
breakdown "--model googlenet"  googlenet_breakdown
micro matmul_tiles
bench transformer  bench_transformer.json
echo "r4b suite done"
