#!/bin/bash
# Chained behind run_chip_r4b.sh: waits for that runner to drain, then
# lands the conv-lowering A/B receipt (native vs im2col at conv1, native
# vs split at the grouped convs) that decides layers/conv.py's
# conv_lowering auto policy.  Same per-step tunnel gate + durability
# contract as r4b.
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

# match the interpreter invocation specifically, not any cmdline that
# happens to contain the script name (a tail/editor would deadlock this
# gate; a bare substring also fails open pre-spawn — launch r4c AFTER r4b)
while pgrep -f "bash tools/run_chip_r4b.sh" >/dev/null 2>&1 ||
      pgrep -f "bash .*/run_chip_r4b.sh" >/dev/null 2>&1; do
    sleep 120
done
wait_tunnel "$OUT/r4c.marker"

f="$OUT/conv_lowering.json"
timeout 2700 python tools/conv_lowering_bench.py --json "$f" \
    > "$OUT/conv_lowering.log" 2>&1 ||
    [ -s "$f" ] || echo '{"error":"killed/timeout","results":[]}' > "$f"
save_receipts "$f" "$OUT/conv_lowering.log"
echo "conv lowering bench done"
