#!/bin/bash
# One-shot (re)launcher for the whole round-5 chip-receipt chain.  Each
# stage is idempotent (receipt_ok skip) and self-orders via pgrep waits,
# so this is safe to run at any time — after a session restart, a
# tunnel recovery, or just to be sure everything is armed.
#
#   bash tools/run_chip_r5_all.sh
set -e
cd "$(dirname "$(dirname "$(readlink -f "$0")")")"
for s in run_chip_pending run_chip_r5b run_chip_r5c run_chip_r5d run_chip_r5e run_chip_r5f; do
    if pgrep -f "^bash tools/$s.sh" > /dev/null; then
        echo "$s: already running"
    else
        nohup bash "tools/$s.sh" > "/tmp/${s}_driver.log" 2>&1 &
        echo "$s: launched ($!)"
    fi
    sleep 1
done
pgrep -af '^bash tools/run_chip'
