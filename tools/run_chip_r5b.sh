#!/bin/bash
# Round-5 chained chip runner: waits for run_chip_pending.sh to drain,
# then lands the NEW round-5 receipts (eval-path fc8 gate A/B).  Safe to
# relaunch (receipt_ok skip); per-step tunnel gate; receipts committed
# as they land.  Separate file because editing a script bash is
# currently executing corrupts the running instance.
#
#   nohup bash tools/run_chip_r5b.sh &
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

while pgrep -f 'bash tools/run_chip_pending.sh' > /dev/null; do
    sleep 120
done

receipt_ok() {
    python - "$1" <<'EOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    raise SystemExit(1)
bad = (d.get('error') is not None or d.get('partial')
       or d.get('superseded')
       or ('value' in d and d['value'] is None))
raise SystemExit(1 if bad else 0)
EOF
}

run_bench() {
    f="$OUT/$2"
    if receipt_ok "$f"; then echo "skip $2 (receipt ok)"; return; fi
    wait_tunnel "$OUT/pending.marker"
    timeout 2700 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save_receipts "$f" "$OUT/$2.log"
}

run_bench eval_alexnet bench_eval_alexnet.json
echo "r5b suite done"
