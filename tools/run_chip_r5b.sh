#!/bin/bash
# Round-5 chained chip runner: waits for run_chip_pending.sh to drain,
# then lands the NEW round-5 receipts (eval-path fc8 gate A/B).  Safe to
# relaunch (receipt_ok skip); per-step tunnel gate; receipts committed
# as they land.  Separate file because replacing a script bash is
# currently executing needs a rename, not an in-place edit.
#
#   nohup bash tools/run_chip_r5b.sh &
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

wait_for_runners run_chip_pending

run_bench_receipt eval_alexnet bench_eval_alexnet.json
echo "r5b suite done"
