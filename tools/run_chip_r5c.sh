#!/bin/bash
# Round-5 chained chip runner, stage c: waits for the pending suite AND
# r5b, then lands the flash-engage receipt (VERDICT r4 task 5's second
# half).  Idempotent; helpers from tools/tunnel_lib.sh.
#
#   nohup bash tools/run_chip_r5c.sh &
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

# wait for BOTH upstream stages: if the pending suite's wall-clock-
# sensitive benches still run, the probe would share the single host
# core with them and contaminate those receipts
wait_for_runners run_chip_pending run_chip_r5b

run_tool_receipt flash_engage python tools/flash_engage_probe.py
echo "r5c suite done"
