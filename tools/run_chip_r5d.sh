#!/bin/bash
# Round-5 chained chip runner, stage d: net-level execution-plan A/Bs.
# Waits for the r5c stage (which itself waits on pending + r5b), then
# lands, each vs the committed baseline receipts:
#   bench_googlenet_blockdiag.json — inception tower fusion (auto:96)
#     vs bench_googlenet.json (VERDICT r4 task 4's measured gate)
#   bench_alexnet_{s2d,im2col,split}.json — conv-lowering variants vs
#     bench_alexnet_lrngate.json (VERDICT r4 task 3's net-level confirm;
#     the micro conv_lowering receipt attributes, these decide)
# Idempotent; helpers from tools/tunnel_lib.sh.
#
#   nohup bash tools/run_chip_r5d.sh &
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

while pgrep -f '^bash tools/run_chip_pending.sh' > /dev/null ||
      pgrep -f '^bash tools/run_chip_r5b.sh' > /dev/null ||
      pgrep -f '^bash tools/run_chip_r5c.sh' > /dev/null; do
    sleep 120
done

run_ab() {    # $1 receipt basename, $2 bench mode, $3 CXXNET_BENCH_CONF_EXTRA
    local f="$OUT/$1"
    if receipt_ok "$f"; then echo "skip $1 (receipt ok)"; return; fi
    wait_tunnel "$OUT/pending.marker"
    timeout 2700 env CXXNET_BENCH_CONF_EXTRA="$3" python bench.py "$2" \
        > "$f" 2>"$OUT/$1.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$2"'","value":null,"error":"killed/timeout"}' > "$f"
    save_receipts "$f" "$OUT/$1.log"
}

run_ab bench_googlenet_blockdiag.json googlenet 'fuse_blockdiag = auto'
run_ab bench_alexnet_s2d.json    alexnet 'conv_lowering = s2d'
run_ab bench_alexnet_im2col.json alexnet 'conv_lowering = im2col'
run_ab bench_alexnet_split.json  alexnet 'conv_lowering = split'
echo "r5d suite done"
