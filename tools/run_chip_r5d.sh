#!/bin/bash
# Round-5 chained chip runner, stage d: net-level execution-plan A/Bs.
# Waits for the r5c stage (which itself waits on pending + r5b), then
# lands, each vs the committed baseline receipts:
#   bench_googlenet_blockdiag.json — inception tower fusion (auto:96)
#     vs bench_googlenet.json (VERDICT r4 task 4's measured gate)
#   bench_alexnet_{s2d,im2col,split}.json — conv-lowering variants vs
#     bench_alexnet_lrngate.json (VERDICT r4 task 3's net-level confirm;
#     the micro conv_lowering receipt attributes, these decide)
# Idempotent; helpers from tools/tunnel_lib.sh.
#
#   nohup bash tools/run_chip_r5d.sh &
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

wait_for_runners run_chip_pending run_chip_r5b run_chip_r5c

run_bench_receipt googlenet bench_googlenet_blockdiag.json 'fuse_blockdiag = auto'
run_bench_receipt alexnet bench_alexnet_s2d.json    'conv_lowering = s2d'
run_bench_receipt alexnet bench_alexnet_im2col.json 'conv_lowering = im2col'
run_bench_receipt alexnet bench_alexnet_split.json  'conv_lowering = split'
echo "r5d suite done"
