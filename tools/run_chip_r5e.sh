#!/bin/bash
# Round-5 chained chip runner, stage e: Inception-BN tower-fusion A/B —
# the second concat-tower family (MFU 0.299 vs GoogLeNet's 0.152); its
# fuse_blockdiag default is gated on THIS receipt, not GoogLeNet's.
# Idempotent; helpers from tools/tunnel_lib.sh.
#
#   nohup bash tools/run_chip_r5e.sh &
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

wait_for_runners run_chip_pending run_chip_r5b run_chip_r5c run_chip_r5d

run_bench_receipt inception_bn bench_inception_bn_blockdiag.json 'fuse_blockdiag = auto'
echo "r5e suite done"
