#!/bin/bash
# Round-5 chained chip runner, stage f: decode (inference) tokens/sec —
# the KV-cached generate path on the GPT-2-small-class LM.  Idempotent;
# helpers from tools/tunnel_lib.sh.
#
#   nohup bash tools/run_chip_r5f.sh &
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1
. tools/tunnel_lib.sh

wait_for_runners run_chip_pending run_chip_r5b run_chip_r5c run_chip_r5d run_chip_r5e

run_bench_receipt decode bench_decode.json
echo "r5f suite done"
