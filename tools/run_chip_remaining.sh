#!/bin/bash
# bash, not sh: the tunnel probe uses /dev/tcp, a bash-ism (dash fails it
# unconditionally, which leaves the watcher polling forever on a live chip).
# Remaining r4 chip work, gated on tunnel health: the axon tunnel died
# mid-suite a second time (16:05 UTC, after the 06:30-15:39 outage), so
# this script polls until the chip answers and then runs every step the
# killed suite hadn't finished, cheapest-first, committing each receipt
# the moment it exists (same durability contract as run_chip_suite.sh).
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1

# probe shared with every chip watcher (bash-only /dev/tcp)
. "$REPO/tools/tunnel_lib.sh"

wait_tunnel "$OUT/remaining_r4.marker"

save() {    # shared impl in tunnel_lib.sh
    save_receipts "$@"
}

micro() {
    f="$OUT/micro_$1.json"
    timeout 2400 python tools/pallas_microbench.py --only "$1" \
        --json "$f" > "$OUT/micro_$1.log" 2>&1
    save "$f" "$OUT/micro_$1.log"
}

bench() {
    f="$OUT/$2"
    env $3 timeout 2700 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save "$f" "$OUT/$2.log"
}

# cheapest-first; matmul_bwd re-measures the shape-adaptive tile clamp
micro matmul_bwd
bench mnist_tta    bench_mnist_tta.json
bench alexnet      bench_alexnet_lrngate.json
# bench_e2e.json is the HOST-normalize A-side of the uint8-wire A/B
# (bench.py defaults to CXXNET_E2E_DEVNORM=1 since the device_normalize
# feature; the B-side lives in bench_e2e_devnorm.json via run_chip_r4b.sh)
bench e2e_alexnet  bench_e2e.json  CXXNET_E2E_DEVNORM=0
timeout 2700 python tools/alexnet_breakdown.py \
    --json "$OUT/alexnet_breakdown.json" > "$OUT/alexnet_breakdown.log" 2>&1
save "$OUT/alexnet_breakdown.json" "$OUT/alexnet_breakdown.log"
timeout 2700 python tools/alexnet_breakdown.py --model googlenet \
    --json "$OUT/googlenet_breakdown.json" > "$OUT/googlenet_breakdown.log" 2>&1
save "$OUT/googlenet_breakdown.json" "$OUT/googlenet_breakdown.log"
micro matmul_tiles
echo "remaining suite done"
