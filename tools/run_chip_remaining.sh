#!/bin/bash
# bash, not sh: the tunnel probe uses /dev/tcp, a bash-ism (dash fails it
# unconditionally, which leaves the watcher polling forever on a live chip).
# Remaining r4 chip work, gated on tunnel health: the axon tunnel died
# mid-suite a second time (16:05 UTC, after the 06:30-15:39 outage), so
# this script polls until the chip answers and then runs every step the
# killed suite hadn't finished, cheapest-first, committing each receipt
# the moment it exists (same durability contract as run_chip_suite.sh).
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1

tunnel_up() {
    # the port-8083 compile helper refusing connections is the reliable
    # down-marker; confirm with a real device probe (which can hang when
    # half-up, hence the timeout)
    (echo > /dev/tcp/127.0.0.1/8083) 2>/dev/null || return 1
    timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

until tunnel_up; do
    sleep 120
done
echo "tunnel up at $(date -u)" >> "$OUT/remaining_r4.marker"

save() {
    for p in "$@"; do
        [ -e "$p" ] && git add "$p"
    done
    if ! git diff --cached --quiet -- "$@"; then
        git commit -q -m "receipts: $(basename "$1" .json)" -- "$@" ||
            echo "WARNING: receipts NOT committed: $*" >&2
    fi
}

micro() {
    f="$OUT/micro_$1.json"
    timeout 2400 python tools/pallas_microbench.py --only "$1" \
        --json "$f" > "$OUT/micro_$1.log" 2>&1
    save "$f" "$OUT/micro_$1.log"
}

bench() {
    f="$OUT/$2"
    env $3 timeout 2700 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save "$f" "$OUT/$2.log"
}

# cheapest-first; matmul_bwd re-measures the shape-adaptive tile clamp
micro matmul_bwd
bench mnist_tta    bench_mnist_tta.json
bench alexnet      bench_alexnet_lrngate.json
bench e2e_alexnet  bench_e2e.json
timeout 2700 python tools/alexnet_breakdown.py \
    --json "$OUT/alexnet_breakdown.json" > "$OUT/alexnet_breakdown.log" 2>&1
save "$OUT/alexnet_breakdown.json" "$OUT/alexnet_breakdown.log"
timeout 2700 python tools/alexnet_breakdown.py --model googlenet \
    --json "$OUT/googlenet_breakdown.json" > "$OUT/googlenet_breakdown.log" 2>&1
save "$OUT/googlenet_breakdown.json" "$OUT/googlenet_breakdown.log"
micro matmul_tiles
echo "remaining suite done"
