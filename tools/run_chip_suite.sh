#!/bin/sh
# One-shot on-chip measurement suite: run when the TPU tunnel is up.
# Produces the per-op Pallas receipts, the AlexNet per-layer breakdown,
# and the BASELINE.md bench rows, each as JSON under $OUT (default
# /tmp/chip_suite). Each step is independently timeout-bounded so a
# tunnel wedge mid-suite still leaves the earlier results on disk.
set -x
OUT=${OUT:-/tmp/chip_suite}
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
mkdir -p "$OUT"
cd "$REPO" || exit 1

timeout 900 python tools/pallas_microbench.py --steps 50 --only lrn \
    --json "$OUT/micro_lrn.json"      > "$OUT/micro_lrn.log" 2>&1
timeout 900 python tools/pallas_microbench.py --steps 50 --only matmul \
    --json "$OUT/micro_matmul.json"   > "$OUT/micro_matmul.log" 2>&1
timeout 1200 python tools/pallas_microbench.py --steps 50 --only attn \
    --json "$OUT/micro_attn.json"     > "$OUT/micro_attn.log" 2>&1
timeout 1200 python tools/alexnet_breakdown.py \
    --json "$OUT/alexnet_breakdown.json" > "$OUT/alexnet_breakdown.log" 2>&1
bench() {  # bench <mode> <outfile> [env]
    f="$OUT/$2"
    env $3 timeout 900 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
}
bench alexnet     bench_alexnet.json
bench alexnet     bench_alexnet_pallas.json CXXNET_PALLAS=1
bench vgg16       bench_vgg16.json
bench e2e_alexnet bench_e2e.json
echo "chip suite done; results in $OUT"
ls -la "$OUT"
