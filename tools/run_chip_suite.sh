#!/bin/sh
# One-shot on-chip measurement suite: run when the TPU tunnel is up.
#
# Durability contract (round-3 postmortem: a round-end kill lost the most
# valuable artifacts because they were written to /tmp and ordered
# expensive-last):
#   * every receipt lands in the tracked receipts/ dir the moment the
#     producing step finishes, and is git-committed immediately;
#   * steps run cheapest-first, so an interrupt loses only the tail;
#   * each step is independently timeout-bounded so a tunnel wedge
#     mid-suite still leaves the earlier results committed.
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
mkdir -p "$OUT"
cd "$REPO" || exit 1

save() {  # save <file...> — commit receipts the moment they exist
    # add files one by one, skipping absent ones: a wedged step may leave
    # only the .log, and `git add missing.json step.log` would abort
    # having staged NOTHING — losing the log, the one artifact a wedge
    # produces
    for p in "$@"; do
        [ -e "$p" ] && git add "$p"
    done
    # unchanged receipts (re-run of a finished step) are a quiet no-op;
    # any real commit failure must be LOUD — silently uncommitted
    # receipts are the round-3 failure mode this script exists to prevent
    if ! git diff --cached --quiet -- "$@"; then
        git commit -q -m "receipts: $(basename "$1" .json)" -- "$@" ||
            echo "WARNING: receipts NOT committed: $*" >&2
    fi
}

micro() {  # micro <only> — pallas-vs-xla microbench (iterations auto-sized)
    f="$OUT/micro_$1.json"
    timeout 2400 python tools/pallas_microbench.py --only "$1" \
        --json "$f" > "$OUT/micro_$1.log" 2>&1
    save "$f" "$OUT/micro_$1.log"
}

bench() {  # bench <mode> <outfile> [env]
    # 2700s: first compile of the train-step scan takes >20 min over the
    # tunnel (the persistent compile cache makes reruns fast)
    f="$OUT/$2"
    env $3 timeout 2700 python bench.py "$1" > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save "$f" "$OUT/$2.log"
}

# -- cheapest first ---------------------------------------------------------
micro lrn
micro matmul
bench alexnet      bench_alexnet.json
bench vgg16        bench_vgg16.json
bench googlenet    bench_googlenet.json
micro attn
bench inception_bn bench_inception_bn.json
bench googlenet    bench_googlenet_b256.json CXXNET_BENCH_BATCH=256
micro matmul_bwd
micro matmul_tiles
timeout 2700 python tools/alexnet_breakdown.py \
    --json "$OUT/alexnet_breakdown.json" > "$OUT/alexnet_breakdown.log" 2>&1
save "$OUT/alexnet_breakdown.json" "$OUT/alexnet_breakdown.log"
timeout 2700 python tools/alexnet_breakdown.py --model googlenet \
    --json "$OUT/googlenet_breakdown.json" > "$OUT/googlenet_breakdown.log" 2>&1
save "$OUT/googlenet_breakdown.json" "$OUT/googlenet_breakdown.log"
bench e2e_alexnet  bench_e2e.json
echo "chip suite done; results committed under $OUT"
ls -la "$OUT"
