#!/bin/bash
# bash, not sh: the tunnel probe uses /dev/tcp, a bash-ism.
# Chained behind run_chip_remaining.sh (which predates the transformer
# bench mode): waits for that runner to drain and the tunnel to answer,
# then lands the TransformerLM tokens/sec receipt.
set -x
REPO=$(dirname "$(dirname "$(readlink -f "$0")")")
OUT=${OUT:-$REPO/receipts}
cd "$REPO" || exit 1

. "$REPO/tools/tunnel_lib.sh"

while pgrep -f run_chip_remaining.sh >/dev/null 2>&1; do
    sleep 120
done
wait_tunnel

f="$OUT/bench_transformer.json"
timeout 2700 python bench.py transformer > "$f" 2>"$OUT/bench_transformer.json.log" ||
    [ -s "$f" ] || echo '{"metric":"transformer","value":null,"error":"killed/timeout"}' > "$f"
git add "$f" "$OUT/bench_transformer.json.log" 2>/dev/null
git diff --cached --quiet -- "$f" || git commit -q -m "receipts: bench_transformer" -- "$f" "$OUT/bench_transformer.json.log"
echo "transformer bench done"
