# Shared tunnel-health probe for the chip watcher scripts.  bash only:
# the port probe is /dev/tcp, a bash-ism that fails unconditionally under
# sh/dash (which once burned a whole round of polling — see
# receipts/remaining_r4.log's correction note).  Source this file; do not
# execute it.
#
# tunnel_up   — one probe: port-8083 compile helper answering AND a real
#               device enumeration completing (can hang half-up, hence
#               the timeout).
# wait_tunnel — block until tunnel_up succeeds, logging the recovery
#               time to $1 (a marker file) when given.

tunnel_up() {
    (echo > /dev/tcp/127.0.0.1/8083) 2>/dev/null || return 1
    timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

# save_receipts <file>... — git-add the given receipt files and commit them
# the moment they exist (the durability contract every watcher shares:
# a kill can only lose the in-flight step, never a produced receipt).
save_receipts() {
    local p
    for p in "$@"; do
        [ -e "$p" ] && git add "$p"
    done
    if ! git diff --cached --quiet -- "$@"; then
        git commit -q -m "receipts: $(basename "$1" .json)" -- "$@" ||
            echo "WARNING: receipts NOT committed: $*" >&2
    fi
}

wait_tunnel() {
    local marker="$1" waited=0
    until tunnel_up; do
        sleep 120
        waited=$((waited + 120))
    done
    if [ -n "$marker" ]; then
        echo "tunnel up at $(date -u) (waited ~${waited}s)" >> "$marker"
    fi
}

# wait_for_runners <script-basename>... — block until none of the named
# runner stages is alive.  Two pgreps, not one with \| (a \| inside a
# pgrep -f pattern is a literal pipe in its ERE and never matches).
# The pattern matches the script PATH SUFFIX ('bash [^ ]*tools/<s>.sh'),
# not an anchored '^bash tools/' — runners launched by absolute path
# ('bash /root/repo/tools/foo.sh', cron, another cwd) must count as
# alive too.  [^ ]* (not .*) keeps the match inside the FIRST argument
# after 'bash ', so a wrapper whose cmdline merely mentions the script
# later ('bash tools/notify.sh tools/foo.sh') still does not count.
wait_for_runners() {
    local s alive=1
    while [ "$alive" -eq 1 ]; do
        alive=0
        for s in "$@"; do
            pgrep -f "bash [^ ]*tools/$s\.sh" > /dev/null && alive=1
        done
        [ "$alive" -eq 1 ] && sleep 120
    done
}

# receipt_ok <file> — 0 when the receipt exists, parses, and is neither
# partial, superseded, nor error-marked (a null value also counts as
# failed).  THE definition of "this step already ran" for every
# idempotent runner — change it here, not in the runner scripts.
receipt_ok() {
    python - "$1" <<'EOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    raise SystemExit(1)
bad = (d.get('error') is not None or d.get('partial')
       or d.get('superseded')
       or ('value' in d and d['value'] is None))
raise SystemExit(1 if bad else 0)
EOF
}

# run_bench_receipt <mode> <receipt-basename> [extra-conf] — bench.py
# JSON-on-stdout into $OUT/<basename>, skip-if-ok, tunnel-gated,
# committed on landing.  $3 (optional) rides CXXNET_BENCH_CONF_EXTRA
# (';'-separated config lines) for execution-plan A/Bs.
run_bench_receipt() {
    local f="$OUT/$2"
    if receipt_ok "$f"; then echo "skip $2 (receipt ok)"; return; fi
    wait_tunnel "$OUT/pending.marker"
    timeout 2700 env CXXNET_BENCH_CONF_EXTRA="${3:-}" python bench.py "$1" \
        > "$f" 2>"$OUT/$2.log" ||
        [ -s "$f" ] || echo '{"metric":"'"$1"'","value":null,"error":"killed/timeout"}' > "$f"
    save_receipts "$f" "$OUT/$2.log"
}

# run_tool_receipt <receipt-basename> <command>... — tools with --json
run_tool_receipt() {
    local f="$OUT/$1.json" log="$OUT/$1.log"
    shift
    if receipt_ok "$f"; then echo "skip $(basename "$f") (receipt ok)"; return; fi
    wait_tunnel "$OUT/pending.marker"
    timeout 2700 "$@" --json "$f" > "$log" 2>&1
    save_receipts "$f" "$log"
}
