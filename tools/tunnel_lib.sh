# Shared tunnel-health probe for the chip watcher scripts.  bash only:
# the port probe is /dev/tcp, a bash-ism that fails unconditionally under
# sh/dash (which once burned a whole round of polling — see
# receipts/remaining_r4.log's correction note).  Source this file; do not
# execute it.
#
# tunnel_up   — one probe: port-8083 compile helper answering AND a real
#               device enumeration completing (can hang half-up, hence
#               the timeout).
# wait_tunnel — block until tunnel_up succeeds, logging the recovery
#               time to $1 (a marker file) when given.

tunnel_up() {
    (echo > /dev/tcp/127.0.0.1/8083) 2>/dev/null || return 1
    timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

# save_receipts <file>... — git-add the given receipt files and commit them
# the moment they exist (the durability contract every watcher shares:
# a kill can only lose the in-flight step, never a produced receipt).
save_receipts() {
    local p
    for p in "$@"; do
        [ -e "$p" ] && git add "$p"
    done
    if ! git diff --cached --quiet -- "$@"; then
        git commit -q -m "receipts: $(basename "$1" .json)" -- "$@" ||
            echo "WARNING: receipts NOT committed: $*" >&2
    fi
}

wait_tunnel() {
    local marker="$1" waited=0
    until tunnel_up; do
        sleep 120
        waited=$((waited + 120))
    done
    if [ -n "$marker" ]; then
        echo "tunnel up at $(date -u) (waited ~${waited}s)" >> "$marker"
    fi
}
